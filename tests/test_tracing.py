"""Tracing: W3C traceparent parsing + OTLP/HTTP JSON span export.

The reference forwards trace headers into its engine's OTel integration
(reference grpc_server.py:257-263); here the span pipeline itself is
exercised against a local collector.
"""

from __future__ import annotations

import asyncio
import json
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import pytest

from vllm_tgis_adapter_tpu.tracing import extract_trace_context


def test_traceparent_parsing():
    good = {
        "traceparent":
            "00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01",
    }
    ctx = extract_trace_context(good)
    assert ctx.trace_id == "0af7651916cd43dd8448eb211c80319c"
    assert ctx.parent_span_id == "b7ad6b7169203331"
    assert ctx.sampled

    # case-insensitive header names
    assert extract_trace_context(
        {"Traceparent": good["traceparent"]}
    ) is not None

    # sampled-out flag parses (the tracer then skips the span entirely)
    off = extract_trace_context({
        "traceparent":
            "00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-00",
    })
    assert off is not None and not off.sampled

    for bad in (
        None,
        {},
        {"traceparent": "junk"},
        {"traceparent": "00-short-b7ad6b7169203331-01"},
        {"traceparent": "00-" + "0" * 32 + "-b7ad6b7169203331-01"},
        {"traceparent":
         "00-0af7651916cd43dd8448eb211c80319c-" + "0" * 16 + "-01"},
        {"traceparent":
         "00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-zz"},
        # right lengths, non-hex: must be rejected, not exported broken
        {"traceparent": "00-" + "z" * 32 + "-b7ad6b7169203331-01"},
        {"traceparent":
         "00-0af7651916cd43dd8448eb211c80319c-" + "z" * 16 + "-01"},
    ):
        assert extract_trace_context(bad) is None


def test_sampled_out_requests_produce_no_span():
    from vllm_tgis_adapter_tpu.tracing import RequestTracer

    tracer = RequestTracer.__new__(RequestTracer)  # no exporter needed
    span = RequestTracer.start_span(
        tracer, "rid",
        {"traceparent":
         "00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-00"},
    )
    assert span is None


class _Collector(BaseHTTPRequestHandler):
    received: list = []

    def do_POST(self):  # noqa: N802
        length = int(self.headers["Content-Length"])
        _Collector.received.append(
            (self.path, json.loads(self.rfile.read(length)))
        )
        self.send_response(200)
        self.end_headers()

    def log_message(self, *args):  # noqa: ANN002
        pass


@pytest.fixture()
def collector():
    _Collector.received = []
    server = HTTPServer(("127.0.0.1", 0), _Collector)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    yield f"http://127.0.0.1:{server.server_port}", _Collector.received
    server.shutdown()


def test_request_span_exported_end_to_end(tiny_model_dir, collector):
    """A generate() call with a traceparent produces one OTLP span with
    the caller's trace id, the parent span id, and token-usage
    attributes."""
    endpoint, received = collector

    from vllm_tgis_adapter_tpu.engine.async_llm import AsyncLLMEngine
    from vllm_tgis_adapter_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        LoRAConfig,
        ModelConfig,
        ParallelConfig,
        SchedulerConfig,
    )
    from vllm_tgis_adapter_tpu.engine.sampling_params import SamplingParams

    mcfg = ModelConfig.from_pretrained(tiny_model_dir, dtype="float32")
    config = EngineConfig(
        model_config=mcfg,
        cache_config=CacheConfig(block_size=16, num_blocks=32,
                                 cache_dtype=mcfg.dtype),
        scheduler_config=SchedulerConfig(max_num_seqs=2,
                                         prefill_buckets=(32,)),
        parallel_config=ParallelConfig(),
        lora_config=LoRAConfig(),
        otlp_traces_endpoint=endpoint,
    )
    engine = AsyncLLMEngine.from_config(config)

    trace_id = "0af7651916cd43dd8448eb211c80319c"
    parent = "b7ad6b7169203331"

    async def scenario():
        assert await engine.is_tracing_enabled()
        async for _ in engine.generate(
            prompt=None,
            sampling_params=SamplingParams(
                temperature=0.0, max_tokens=5, ignore_eos=True
            ),
            request_id="traced-1",
            prompt_token_ids=list(range(3, 10)),
            trace_headers={
                "traceparent": f"00-{trace_id}-{parent}-01"
            },
        ):
            pass
        await engine.stop()  # flushes the export queue (tracer shutdown)

    asyncio.run(scenario())

    assert received, "no OTLP batch reached the collector"
    assert all(path == "/v1/traces" for path, _ in received)
    spans = [
        s
        for _, payload in received
        for s in payload["resourceSpans"][0]["scopeSpans"][0]["spans"]
    ]
    span = next(
        s for s in spans
        if s["traceId"] == trace_id and s["name"] == "llm_request"
    )
    assert span["parentSpanId"] == parent
    attrs = {a["key"]: a["value"] for a in span["attributes"]}
    assert attrs["gen_ai.request.id"]["stringValue"] == "traced-1"
    assert attrs["gen_ai.usage.prompt_tokens"]["intValue"] == "7"
    assert attrs["gen_ai.usage.completion_tokens"]["intValue"] == "5"
    assert int(span["endTimeUnixNano"]) > int(span["startTimeUnixNano"])

    # phase child spans: same trace, parented under the request span,
    # time-ordered and contained within the request span's window
    children = {
        s["name"]: s
        for s in spans
        if s.get("parentSpanId") == span["spanId"]
    }
    assert {"queue", "prefill", "decode"} <= set(children)
    for child in children.values():
        assert child["traceId"] == trace_id
        assert child["kind"] == 1  # SPAN_KIND_INTERNAL
        assert int(child["startTimeUnixNano"]) >= int(
            span["startTimeUnixNano"]
        )
        assert int(child["endTimeUnixNano"]) <= int(span["endTimeUnixNano"])
    assert int(children["queue"]["endTimeUnixNano"]) <= int(
        children["prefill"]["startTimeUnixNano"]
    )
    assert int(children["prefill"]["endTimeUnixNano"]) <= int(
        children["decode"]["startTimeUnixNano"]
    )


def test_http_completions_propagate_trace_context(
    tiny_model_dir, collector
):
    """A traceparent header on /v1/completions reaches the engine: the
    request span joins the caller's trace (the same propagation the gRPC
    server does via invocation metadata)."""
    import argparse

    endpoint, received = collector

    from vllm_tgis_adapter_tpu.engine.async_llm import AsyncLLMEngine
    from vllm_tgis_adapter_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        LoRAConfig,
        ModelConfig,
        ParallelConfig,
        SchedulerConfig,
    )
    from vllm_tgis_adapter_tpu.http import HttpRequest, build_http_server

    mcfg = ModelConfig.from_pretrained(tiny_model_dir, dtype="float32")
    config = EngineConfig(
        model_config=mcfg,
        cache_config=CacheConfig(block_size=16, num_blocks=32,
                                 cache_dtype=mcfg.dtype),
        scheduler_config=SchedulerConfig(max_num_seqs=2,
                                         prefill_buckets=(32,)),
        parallel_config=ParallelConfig(),
        lora_config=LoRAConfig(),
        otlp_traces_endpoint=endpoint,
    )
    engine = AsyncLLMEngine.from_config(config)
    args = argparse.Namespace(
        served_model_name=None, model=tiny_model_dir, api_key=None,
        root_path=None, profile_dir=None,
    )
    app = build_http_server(args, engine)
    trace_id = "1bf7651916cd43dd8448eb211c80319c"

    async def scenario() -> int:
        response = await app.dispatch(HttpRequest(
            "POST", "/v1/completions",
            {"traceparent": f"00-{trace_id}-b7ad6b7169203331-01"},
            json.dumps({
                "prompt": "Hi", "max_tokens": 3, "temperature": 0.0,
            }).encode(),
        ))
        await engine.stop()
        return response.status

    assert asyncio.run(scenario()) == 200
    spans = [
        s
        for _, payload in received
        for s in payload["resourceSpans"][0]["scopeSpans"][0]["spans"]
    ]
    assert any(
        s["name"] == "llm_request" and s["traceId"] == trace_id
        for s in spans
    ), "HTTP traceparent did not reach the engine span"


def test_exporter_flushes_partial_batch_on_shutdown(collector):
    """Spans still queued at shutdown — fewer than _EXPORT_BATCH, some
    racing the sentinel — must all reach the collector before close."""
    import time

    from vllm_tgis_adapter_tpu.tracing import OtlpJsonExporter, Span

    endpoint, received = collector
    exporter = OtlpJsonExporter(endpoint)
    now = time.time_ns()
    for i in range(5):
        exporter.export(
            Span(
                name=f"s{i}",
                trace_id="ab" * 16,
                span_id=f"{i:016x}",
                parent_span_id=None,
                start_ns=now,
                end_ns=now + 1,
            )
        )
    exporter.shutdown()
    names = {
        s["name"]
        for _, payload in received
        for s in payload["resourceSpans"][0]["scopeSpans"][0]["spans"]
    }
    assert names == {f"s{i}" for i in range(5)}, "spans dropped on close"


@pytest.mark.parametrize("path", ["local", "cross_replica", "handoff"])
def test_resume_span_links_to_origin(collector, path):
    """Every recovery hop emits a marker span that joins the origin's
    trace AND carries an explicit OTLP span link to the originating
    request span — the queryable relationship ("every request this
    migration touched") that sharing a trace_id alone does not give a
    backend."""
    from vllm_tgis_adapter_tpu.tracing import RequestTracer

    endpoint, received = collector
    tracer = RequestTracer(endpoint)
    origin = tracer.start_span(
        "resumed-1",
        {"traceparent":
         "00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01"},
    )
    marker = tracer.resume_span(origin, "resumed-1", path)
    assert marker.links == [(origin.trace_id, origin.span_id)]
    tracer.shutdown()

    spans = [
        s
        for _, payload in received
        for s in payload["resourceSpans"][0]["scopeSpans"][0]["spans"]
    ]
    resume = next(s for s in spans if s["name"] == "llm_request.resume")
    # joins the origin's trace, parented under the request span
    assert resume["traceId"] == origin.trace_id
    assert resume["parentSpanId"] == origin.span_id
    assert resume["kind"] == 1  # SPAN_KIND_INTERNAL
    # the explicit link — both halves of the origin's identity
    assert resume["links"] == [
        {"traceId": origin.trace_id, "spanId": origin.span_id}
    ]
    attrs = {a["key"]: a["value"] for a in resume["attributes"]}
    assert attrs["path"]["stringValue"] == path
    assert attrs["gen_ai.request.id"]["stringValue"] == "resumed-1"
    # zero-duration marker: recovery COST lives in the restart/handoff
    # histograms, not in span length
    assert resume["startTimeUnixNano"] == resume["endTimeUnixNano"]
