"""GPT-NeoX / Pythia family: numerical parity vs HF torch + engine e2e.

Fourth architecture family through the shared decoder skeleton
(models/llama.py): partial rotary (rotary_pct of each head), parallel
attention+MLP residual, pre-LayerNorm with biases, fused head-interleaved
query_key_value checkpoints (de-interleaved at load,
engine/weights.py load_gpt_neox_params), untied embed_out lm_head.

Gold-standard checks mirror tests/test_model_correctness.py.
"""

from __future__ import annotations

import numpy as np
import pytest

from tests.fixture_models import hf_reference_model, hf_tokenize


@pytest.fixture(scope="module")
def neox_dir(tmp_path_factory):
    from tests.fixture_models import build_tiny_gpt_neox

    return build_tiny_gpt_neox(str(tmp_path_factory.mktemp("tiny-neox")))


@pytest.fixture(scope="module")
def setup(neox_dir):
    import jax.numpy as jnp

    from vllm_tgis_adapter_tpu.engine.config import ModelConfig
    from vllm_tgis_adapter_tpu.engine.weights import load_model_params
    from vllm_tgis_adapter_tpu.models import get_model_class

    config = ModelConfig.from_pretrained(neox_dir, dtype="float32")
    model = get_model_class(config.model_type)(config)
    params = load_model_params(config, neox_dir)
    caches = model.make_kv_caches(num_slots=1024, dtype=jnp.float32)
    return neox_dir, config, model, params, caches


def test_neox_config_mapping(setup):
    _, config, _, params, _ = setup
    assert config.model_type == "gpt_neox"
    assert config.parallel_residual
    assert config.rotary_dim == 4  # head_dim 16 × rotary_pct 0.25
    assert config.norm_type == "layernorm"
    assert not config.gated_mlp and config.hidden_act == "gelu"
    assert "lm_head" in params  # untied embed_out
    layer = params["layers"][0]
    # fused qkv was de-interleaved into standard projections
    for name in ("wq", "wk", "wv", "bq", "bk", "bv", "bo",
                 "b_up", "b_down"):
        assert name in layer, name


def test_neox_prefill_logits_match_hf(setup):
    import jax.numpy as jnp
    import torch

    model_dir, config, model, params, caches = setup
    input_ids = hf_tokenize(model_dir, "the quick brown fox jumps")
    t = len(input_ids)

    logits, _ = model.prefill(
        params, caches,
        jnp.asarray(input_ids, dtype=jnp.int32),
        jnp.arange(t, dtype=jnp.int32),
        jnp.arange(t, dtype=jnp.int32),
        jnp.asarray(t, dtype=jnp.int32),
    )
    hf = hf_reference_model(model_dir)
    with torch.no_grad():
        hf_logits = hf(torch.tensor([input_ids])).logits[0].numpy()
    np.testing.assert_allclose(
        np.asarray(logits), hf_logits, rtol=1e-3, atol=1e-3
    )


def test_neox_greedy_decode_matches_hf_generate(setup):
    import jax.numpy as jnp
    import torch

    model_dir, config, model, params, caches = setup
    input_ids = hf_tokenize(model_dir, "the capital of France")
    t = len(input_ids)
    new_tokens = 12
    block_size = 16
    max_blocks = 8

    hf = hf_reference_model(model_dir)
    with torch.no_grad():
        hf_out = hf.generate(
            torch.tensor([input_ids]),
            max_new_tokens=new_tokens,
            do_sample=False,
            eos_token_id=None,
        )[0].tolist()
    expected = hf_out[t:]

    logits, caches = model.prefill(
        params, caches,
        jnp.asarray(input_ids, dtype=jnp.int32),
        jnp.arange(t, dtype=jnp.int32),
        jnp.arange(t, dtype=jnp.int32),
        jnp.asarray(t, dtype=jnp.int32),
    )
    block_tables = jnp.arange(max_blocks, dtype=jnp.int32)[None, :]
    next_token = int(jnp.argmax(logits[t - 1]))
    produced = [next_token]
    pos = t
    for _ in range(new_tokens - 1):
        step_logits, caches = model.decode(
            params, caches,
            jnp.asarray([next_token], dtype=jnp.int32),
            jnp.asarray([pos], dtype=jnp.int32),
            jnp.asarray([pos], dtype=jnp.int32),
            block_tables,
            jnp.asarray([pos + 1], dtype=jnp.int32),
            block_size,
        )
        next_token = int(jnp.argmax(step_logits[0]))
        produced.append(next_token)
        pos += 1

    assert produced == expected


def test_neox_engine_end_to_end(neox_dir):
    from vllm_tgis_adapter_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        LoRAConfig,
        ModelConfig,
        ParallelConfig,
        SchedulerConfig,
    )
    from vllm_tgis_adapter_tpu.engine.core import LLMEngine
    from vllm_tgis_adapter_tpu.engine.sampling_params import SamplingParams

    mcfg = ModelConfig.from_pretrained(neox_dir, dtype="float32")
    config = EngineConfig(
        model_config=mcfg,
        cache_config=CacheConfig(block_size=16, num_blocks=64,
                                 cache_dtype=mcfg.dtype),
        scheduler_config=SchedulerConfig(max_num_seqs=4,
                                         prefill_buckets=(32, 64)),
        parallel_config=ParallelConfig(),
        lora_config=LoRAConfig(),
    )
    engine = LLMEngine.from_config(config)
    for i in range(3):
        engine.add_request(
            f"neox-{i}", f"tell me about topic {i}",
            SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True),
        )
    done = {}
    for _ in range(200):
        if not engine.has_unfinished_requests():
            break
        for out in engine.step():
            if out.finished:
                done[out.request_id] = out
    assert set(done) == {"neox-0", "neox-1", "neox-2"}
    for out in done.values():
        assert len(out.outputs[0].token_ids) == 8


def test_neox_tp2_matches_single_device(neox_dir):
    """The de-interleaved fused QKV must shard correctly: TP=2 logits
    equal single-device logits (the de-interleave put each head's rows
    contiguous, which the Megatron column split requires)."""
    import jax
    import jax.numpy as jnp

    from vllm_tgis_adapter_tpu.engine.config import ModelConfig
    from vllm_tgis_adapter_tpu.engine.weights import load_model_params
    from vllm_tgis_adapter_tpu.models import get_model_class
    from vllm_tgis_adapter_tpu.parallel import build_mesh, make_place_fn

    config = ModelConfig.from_pretrained(neox_dir, dtype="float32")
    model = get_model_class(config.model_type)(config)
    params = load_model_params(config, neox_dir)
    caches = model.make_kv_caches(num_slots=256, dtype=jnp.float32)

    input_ids = hf_tokenize(neox_dir, "sharding parity probe")
    t = len(input_ids)
    args = (
        jnp.asarray(input_ids, dtype=jnp.int32),
        jnp.arange(t, dtype=jnp.int32),
        jnp.arange(t, dtype=jnp.int32),
        jnp.asarray(t, dtype=jnp.int32),
    )
    ref, _ = model.prefill(params, caches, *args)

    mesh = build_mesh(tensor_parallel_size=2,
                      devices=jax.devices()[:2])
    place = make_place_fn(mesh)
    sharded_params = load_model_params(config, neox_dir, place=place)
    tp_model = get_model_class(config.model_type)(config)
    tp_model.mesh = mesh
    from vllm_tgis_adapter_tpu.parallel.sharding import cache_sharding

    tp_caches = jax.device_put(
        model.make_kv_caches(num_slots=256, dtype=jnp.float32),
        cache_sharding(mesh),
    )
    got, _ = tp_model.prefill(sharded_params, tp_caches, *args)
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(got), rtol=1e-4, atol=1e-4
    )
