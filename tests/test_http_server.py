"""Full-stack HTTP integration tests (reference: tests/test_http_server.py)."""

from __future__ import annotations

import json
import urllib.error
import urllib.request

import pytest


def _get(url: str, timeout: float = 30):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read()


def _post_json(url: str, payload: dict, timeout: float = 60):
    req = urllib.request.Request(
        url,
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, resp.read()


def test_startup(http_base_url):
    status, _ = _get(f"{http_base_url}/health")
    assert status == 200


def test_models(http_base_url, server_args):
    status, body = _get(f"{http_base_url}/v1/models")
    assert status == 200
    payload = json.loads(body)
    assert payload["object"] == "list"
    ids = [m["id"] for m in payload["data"]]
    assert server_args.model in ids


def test_completions(http_base_url, server_args):
    status, body = _post_json(
        f"{http_base_url}/v1/completions",
        {
            "model": server_args.model,
            "prompt": "The answer to life the universe",
            "max_tokens": 10,
            "temperature": 0.0,
        },
    )
    assert status == 200
    payload = json.loads(body)
    assert payload["object"] == "text_completion"
    assert len(payload["choices"]) == 1
    assert payload["choices"][0]["text"]
    assert payload["usage"]["completion_tokens"] == 10


def test_completions_batch_prompts(http_base_url, server_args):
    status, body = _post_json(
        f"{http_base_url}/v1/completions",
        {
            "model": server_args.model,
            "prompt": ["Hello", "Goodbye"],
            "max_tokens": 4,
            "temperature": 0.0,
        },
    )
    assert status == 200
    payload = json.loads(body)
    assert len(payload["choices"]) == 2
    assert {c["index"] for c in payload["choices"]} == {0, 1}


def test_completions_stream(http_base_url, server_args):
    req = urllib.request.Request(
        f"{http_base_url}/v1/completions",
        data=json.dumps(
            {
                "model": server_args.model,
                "prompt": "The answer",
                "max_tokens": 5,
                "temperature": 0.0,
                "stream": True,
            }
        ).encode(),
        headers={"Content-Type": "application/json"},
        method="POST",
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        assert resp.status == 200
        assert resp.headers["content-type"].startswith("text/event-stream")
        raw = resp.read().decode()
    events = [
        line[len("data: ") :]
        for line in raw.splitlines()
        if line.startswith("data: ")
    ]
    assert events[-1] == "[DONE]"
    chunks = [json.loads(e) for e in events[:-1]]
    assert len(chunks) == 5
    assert all(c["object"] == "text_completion" for c in chunks)
    assert chunks[-1]["choices"][0]["finish_reason"] == "length"


def test_completions_unknown_model(http_base_url):
    with pytest.raises(urllib.error.HTTPError) as excinfo:
        _post_json(
            f"{http_base_url}/v1/completions",
            {"model": "does-not-exist", "prompt": "hi", "max_tokens": 2},
        )
    assert excinfo.value.code == 404


def test_completions_invalid_params(http_base_url, server_args):
    with pytest.raises(urllib.error.HTTPError) as excinfo:
        _post_json(
            f"{http_base_url}/v1/completions",
            {
                "model": server_args.model,
                "prompt": "hi",
                "max_tokens": 2,
                "temperature": -1.0,
            },
        )
    assert excinfo.value.code == 400


def test_metrics(http_base_url, server_args):
    # generate something first so counters are non-trivial
    _post_json(
        f"{http_base_url}/v1/completions",
        {"model": server_args.model, "prompt": "hi", "max_tokens": 2},
    )
    status, body = _get(f"{http_base_url}/metrics")
    assert status == 200
    text = body.decode()
    assert "tgis_tpu_generated_tokens_total" in text
    # engine-state gauges (VERDICT r3 #6): exported and scrape-fresh
    for gauge in (
        "tgis_tpu_num_requests_waiting",
        "tgis_tpu_kv_pages_total",
        "tgis_tpu_kv_pages_used",
        "tgis_tpu_kv_cache_usage",
        "tgis_tpu_prefix_cache_hit_tokens",
    ):
        assert gauge in text, f"missing gauge {gauge}"
    # the pool exists, so the scrape-time refresh must report its size
    for line in text.splitlines():
        if line.startswith("tgis_tpu_kv_pages_total "):
            assert float(line.split()[1]) > 0
            break
    else:
        raise AssertionError("kv_pages_total sample line missing")


def test_correlation_id_header_roundtrip(http_base_url):
    req = urllib.request.Request(
        f"{http_base_url}/health",
        headers={"X-Correlation-ID": "abc-123"},
    )
    with urllib.request.urlopen(req, timeout=30) as resp:
        assert resp.headers.get("x-correlation-id") == "abc-123"


def test_version(http_base_url):
    status, body = _get(f"{http_base_url}/version")
    assert status == 200
    assert "version" in json.loads(body)


def test_chat_completions(http_base_url):
    _, raw = _post_json(
        f"{http_base_url}/v1/chat/completions",
        {
            "messages": [
                {"role": "system", "content": "You are terse."},
                {"role": "user", "content": "say something"},
            ],
            "max_tokens": 6,
            "temperature": 0,
        },
    )
    resp = json.loads(raw)
    assert resp["object"] == "chat.completion"
    choice = resp["choices"][0]
    assert choice["message"]["role"] == "assistant"
    assert isinstance(choice["message"]["content"], str)
    assert resp["usage"]["completion_tokens"] == 6
    assert resp["usage"]["total_tokens"] == (
        resp["usage"]["prompt_tokens"] + 6
    )


def test_chat_completions_stream(http_base_url):
    _, raw = _post_json(
        f"{http_base_url}/v1/chat/completions",
        {
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 5,
            "temperature": 0,
            "stream": True,
        },
    )
    lines = [
        ln for ln in raw.decode().splitlines() if ln.startswith("data: ")
    ]
    assert lines[-1] == "data: [DONE]"
    chunks = [json.loads(ln[6:]) for ln in lines[:-1]]
    assert chunks[0]["choices"][0]["delta"]["role"] == "assistant"
    text = "".join(
        c["choices"][0]["delta"].get("content", "") for c in chunks
    )
    assert text  # streamed some content
    assert chunks[-1]["choices"][0]["finish_reason"] in ("length", "stop")


def test_chat_completions_validation(http_base_url):
    for bad in ({"messages": "not a list"}, {"messages": []},
                {"messages": [{"role": "user", "content": "x"}], "n": 0}):
        try:
            _post_json(f"{http_base_url}/v1/chat/completions", bad)
            raise AssertionError(f"expected 400 for {bad}")
        except urllib.error.HTTPError as e:
            assert e.code == 400


def test_tokenize_and_detokenize_roundtrip(http_base_url):
    """vLLM-app extras the reference gets by mounting the full OpenAI
    app: /tokenize returns ids+count+max_model_len, /detokenize inverts."""
    status, body = _post_json(
        f"{http_base_url}/tokenize", {"prompt": "hello world"}
    )
    assert status == 200
    tok = json.loads(body)
    assert tok["count"] == len(tok["tokens"]) > 0
    assert tok["max_model_len"] > 0

    status, body = _post_json(
        f"{http_base_url}/detokenize", {"tokens": tok["tokens"]}
    )
    assert status == 200
    assert "hello" in json.loads(body)["prompt"]


def test_tokenize_validation(http_base_url):
    with pytest.raises(urllib.error.HTTPError) as excinfo:
        _post_json(f"{http_base_url}/tokenize", {"prompt": 7})
    assert excinfo.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as excinfo:
        _post_json(f"{http_base_url}/detokenize", {"tokens": "nope"})
    assert excinfo.value.code == 400


def test_root_path_prefix_stripped():
    """--root-path: routes match with the reverse-proxy prefix stripped
    (the flag used to be accepted and ignored — truthful-flag audit)."""
    import asyncio

    from vllm_tgis_adapter_tpu.http import App, HttpRequest, JsonResponse

    app = App(root_path="/proxy/llm")

    @app.route("GET", "/ping")
    async def ping(app, request):  # noqa: ANN001, ARG001
        return JsonResponse({"ok": True})

    def req(path):
        return HttpRequest(method="GET", path=path, headers={}, body=b"")

    ok = asyncio.run(app.dispatch(req("/proxy/llm/ping")))
    assert ok.status == 200
    bare = asyncio.run(app.dispatch(req("/ping")))
    assert bare.status == 200  # unprefixed still works (direct access)
    missing = asyncio.run(app.dispatch(req("/proxy/llm/nope")))
    assert missing.status == 404


def test_root_path_overlapping_native_route():
    """--root-path /v1 must not shadow the native /v1/* routes: a direct
    (unproxied) request to /v1/completions strips to /completions, which
    is unregistered — the dispatcher must fall back to the raw path
    (advisor r4)."""
    import asyncio

    from vllm_tgis_adapter_tpu.http import App, HttpRequest, JsonResponse

    app = App(root_path="/v1")

    @app.route("POST", "/v1/completions")
    async def completions(app, request):  # noqa: ANN001, ARG001
        return JsonResponse({"ok": True})

    def req(path):
        return HttpRequest(method="POST", path=path, headers={}, body=b"")

    direct = asyncio.run(app.dispatch(req("/v1/completions")))
    assert direct.status == 200
    proxied = asyncio.run(app.dispatch(req("/v1/v1/completions")))
    assert proxied.status == 200


def test_completions_n_samples(http_base_url):
    """OpenAI `n`: one prompt expands into n choices (prompt-major
    indices); seeded sampling gives DISTINCT per-sample streams that are
    reproducible as a set; usage counts the prompt once."""
    body = {
        "prompt": "the quick brown",
        "max_tokens": 6,
        "n": 3,
        "temperature": 0.9,
        "seed": 7,
        "ignore_eos": True,
    }
    import json as _json

    _, raw = _post_json(f"{http_base_url}/v1/completions", body)
    first = _json.loads(raw)
    assert [c["index"] for c in first["choices"]] == [0, 1, 2]
    texts = [c["text"] for c in first["choices"]]
    assert len(set(texts)) > 1, "sibling seeds must differ"
    _, raw = _post_json(f"{http_base_url}/v1/completions", body)
    assert [c["text"] for c in _json.loads(raw)["choices"]] == texts

    _, raw = _post_json(f"{http_base_url}/v1/completions", {**body, "n": 1})
    one = _json.loads(raw)
    assert first["usage"]["prompt_tokens"] == one["usage"]["prompt_tokens"]
    assert first["usage"]["completion_tokens"] == 18


def test_chat_completions_n_samples(http_base_url):
    import json as _json

    _, raw = _post_json(f"{http_base_url}/v1/chat/completions", {
        "messages": [{"role": "user", "content": "hi"}],
        "max_tokens": 5,
        "n": 2,
        "temperature": 0.9,
        "seed": 3,
        "ignore_eos": True,
    })
    out = _json.loads(raw)
    assert [c["index"] for c in out["choices"]] == [0, 1]
    assert all(c["message"]["role"] == "assistant" for c in out["choices"])
    assert out["usage"]["completion_tokens"] == 10


def test_completions_n_bounds(http_base_url):
    import urllib.error

    with pytest.raises(urllib.error.HTTPError) as excinfo:
        _post_json(f"{http_base_url}/v1/completions",
                   {"prompt": "x", "n": 0})
    assert excinfo.value.code == 400


def test_debug_state_live(http_base_url, server_args):
    """GET /debug/state over a real socket: full snapshot with queues,
    KV stats, compile-tracker state, and recorder events — and the three
    watchdog/recorder metric families on /metrics (acceptance)."""
    import json as _json

    _post_json(
        f"{http_base_url}/v1/completions",
        {"model": server_args.model, "prompt": "state probe",
         "max_tokens": 3},
    )
    status, body = _get(f"{http_base_url}/debug/state")
    assert status == 200
    state = _json.loads(body)
    assert state["engine"]["running"] is True
    replica = state["replicas"][0]
    assert replica["kv_cache"]["num_blocks"] > 0
    assert "waiting" in replica["scheduler"]
    assert "compiled_shapes" in state["compile_tracker"]
    kinds = {e["kind"] for e in state["events"]}
    assert {"admit", "finish"} <= kinds

    _, body = _get(f"{http_base_url}/metrics")
    text = body.decode()
    for family in (
        "tgis_tpu_flight_recorder_events_total",
        "tgis_tpu_watchdog_last_heartbeat_age_seconds",
        "tgis_tpu_watchdog_stalls_total",
    ):
        assert family in text, f"missing metric {family}"


def test_debug_request_timeline(http_base_url, server_args):
    """GET /debug/requests/{id}: the per-request flight-recorder
    timeline, discovered via the finish events in /debug/state."""
    import json as _json
    import urllib.error

    _post_json(
        f"{http_base_url}/v1/completions",
        {"model": server_args.model, "prompt": "trace me",
         "max_tokens": 3},
    )
    _, body = _get(f"{http_base_url}/debug/state")
    finished = [
        e["request_id"]
        for e in _json.loads(body)["events"]
        if e["kind"] == "finish" and "request_id" in e
    ]
    assert finished
    status, body = _get(
        f"{http_base_url}/debug/requests/{finished[-1]}"
    )
    assert status == 200
    trace = _json.loads(body)
    assert trace["request_id"] == finished[-1]
    kinds = [e["kind"] for e in trace["events"]]
    assert kinds[0] == "admit" and kinds[-1] == "finish"

    with pytest.raises(urllib.error.HTTPError) as excinfo:
        _get(f"{http_base_url}/debug/requests/no-such-request")
    assert excinfo.value.code == 404
