"""Step-time anatomy + unified timeline export (docs/OBSERVABILITY.md,
"Step anatomy & doctor").

CPU-backed: the anatomy-sums-to-wall invariant the decomposition is
built around, host_gap semantics (async vs SYNC_DISPATCH device start,
the GAP_CAP clamp, the idle cutoff), ring bounds, the /debug/state
serializer shape, the stall-snapshot StepRecord embed, the chrome-trace
exporter's golden shape (valid JSON, monotonic ts, stable pid/tid), and
the HTTP surfaces (?section= filtering, /debug/doctor,
/debug/timeline) via the real app dispatch.  The GetTimeline RPC twin
is covered in test_grpc_server.py.
"""

from __future__ import annotations

import asyncio
import json
import re
import time
from types import SimpleNamespace

from vllm_tgis_adapter_tpu.telemetry.steptime import (
    GAP_CAP_S,
    PHASES,
    StepTimeline,
    _Stamps,
)


def _sample(text: str, name: str, labels: tuple[str, ...] = ()) -> float:
    for line in text.splitlines():
        m = re.match(rf"^{re.escape(name)}(\{{[^}}]*\}})? (\S+)$", line)
        if m and all(lbl in (m.group(1) or "") for lbl in labels):
            return float(m.group(2))
    return 0.0


def _scrape() -> str:
    from vllm_tgis_adapter_tpu import metrics

    return metrics.render().decode()


# --------------------------------------------------------------- helpers


def _live_step(tl: StepTimeline, *, step: int = 1, sync: bool = False):
    """Drive one step through the real stamp helpers (live clock)."""
    prepared = SimpleNamespace()
    t_enter = time.perf_counter()
    tl.stamp_plan(prepared, t_enter=t_enter, t_sched=time.perf_counter())
    tl.begin_dispatch(prepared)
    tl.end_dispatch(prepared, sync=sync)
    tl.begin_wait(prepared)
    tl.end_wait(prepared)
    return tl.finish(
        prepared, step=step, replica=0, kind="decode", tokens=8,
        fill_ratio=1.0,
    )


def _stamps_at(base: float, *, sync: bool = False,
               wait1_off: float = 0.006) -> _Stamps:
    """Hand-crafted stamps at fixed offsets from ``base`` so the gap
    arithmetic is deterministic.  Offsets: enter +0, sched +1ms,
    prep +2ms, disp0 +3ms, disp1 +4ms, wait0 +5ms, wait1 +6ms."""
    st = _Stamps()
    st.t_enter = base
    st.t_sched = base + 0.001
    st.t_prep = base + 0.002
    st.t_disp0 = base + 0.003
    st.t_disp1 = base + 0.004
    st.t_wait0 = base + 0.005
    st.t_wait1 = base + wait1_off
    st.sync = sync
    return st


def _crafted_step(
    tl: StepTimeline, *, step: int = 1, sync: bool = False,
    base: float | None = None,
):
    """Drive one crafted step through finish() (see _stamps_at)."""
    if base is None:
        base = time.perf_counter() - 0.01  # keep t_end after the stamps
    prepared = SimpleNamespace(_steptime=_stamps_at(base, sync=sync))
    record = tl.finish(
        prepared, step=step, replica=0, kind="ragged", tokens=32,
        fill_ratio=0.5,
    )
    assert record is not None
    return record, base


# ------------------------------------------------------- sum invariant


def test_anatomy_sums_to_step_wall():
    """The load-bearing contract: the six phases telescope, so their
    sum equals wall_s (= host_gap + (t_end - t_enter)) exactly up to
    float association."""
    tl = StepTimeline()
    for step in range(1, 6):
        record = _live_step(tl, step=step)
        assert record is not None
        phases = record.phases()
        assert tuple(phases) == PHASES
        assert abs(sum(phases.values()) - record.wall_s) < 1e-9
        assert all(v >= 0.0 for v in phases.values())


def test_first_step_has_no_host_gap():
    tl = StepTimeline()
    record = _live_step(tl)
    assert record.host_gap_s == 0.0


def test_host_gap_async_measures_lead_in_from_dispatch():
    """Async dispatch: device work starts at enqueue (t_disp1), so the
    gap is t_disp1 - previous device_end."""
    tl = StepTimeline()
    rec1, base1 = _crafted_step(
        tl, step=1, base=time.perf_counter() - 1.0
    )
    assert rec1.host_gap_s == 0.0  # no previous device_end
    # previous device_end = base1 + 6ms; next disp1 = base2 + 4ms
    base2 = base1 + 0.006 + 0.02 - 0.004  # raw gap: exactly 20ms
    rec2, _ = _crafted_step(tl, step=2, base=base2)
    assert abs(rec2.host_gap_s - 0.02) < 1e-9
    assert abs(sum(rec2.phases().values()) - rec2.wall_s) < 1e-9


def test_host_gap_sync_measures_lead_in_from_wait_entry():
    """SYNC_DISPATCH: the blocking wait entry (t_wait0) is when device
    work can start, and it trails the previous device_end by the full
    serialized host phase — the host_bound discriminator."""
    tl = StepTimeline()
    _, base1 = _crafted_step(
        tl, step=1, sync=True, base=time.perf_counter() - 1.0
    )
    base2 = base1 + 0.006 + 0.03 - 0.005  # wait0 lands 30ms after
    rec2, _ = _crafted_step(tl, step=2, sync=True, base=base2)
    assert abs(rec2.host_gap_s - 0.03) < 1e-9


def test_host_gap_blocking_dispatch_uses_dispatch_window():
    """CPU proxy with async dispatch disabled (BENCH_SYNC_DISPATCH=1):
    the device work runs INSIDE dispatch, so the gap must be measured
    t_disp0 - previous t_disp1 — against the wait stamps it would
    degenerate to ~0 and hide the serialization."""
    tl = StepTimeline(dispatch_blocks=True)
    _, base1 = _crafted_step(
        tl, step=1, base=time.perf_counter() - 1.0
    )
    # previous device_end = t_disp1 = base1 + 4ms; this step's
    # device_start = t_disp0 = base2 + 3ms
    base2 = base1 + 0.004 + 0.04 - 0.003  # raw gap: exactly 40ms
    rec2, _ = _crafted_step(tl, step=2, base=base2)
    assert abs(rec2.host_gap_s - 0.04) < 1e-9
    # under the commit ordering blocking dispatch actually produces —
    # the previous step's (instant) wait retires AFTER this step's
    # dispatch — the wait-stamp reading degenerates to no gap at all
    tl2 = StepTimeline()
    st1 = SimpleNamespace(
        _steptime=_stamps_at(time.perf_counter() - 1.0, wait1_off=0.046)
    )
    tl2.finish(st1, step=1, replica=0, kind="ragged", tokens=1,
               fill_ratio=1.0)
    rec, _ = _crafted_step(
        tl2, step=2,
        base=st1._steptime.t_disp1 + 0.04 - 0.003,
    )
    assert rec.host_gap_s == 0.0


def test_backend_dispatch_blocks_detection():
    import jax

    from vllm_tgis_adapter_tpu.telemetry.steptime import (
        backend_dispatch_blocks,
    )

    assert backend_dispatch_blocks() is False  # suite default: async
    jax.config.update("jax_cpu_enable_async_dispatch", False)
    try:
        assert backend_dispatch_blocks() is True
    finally:
        jax.config.update("jax_cpu_enable_async_dispatch", True)


def test_host_gap_clamped_and_idle_cutoff():
    tl = StepTimeline()
    _, base1 = _crafted_step(
        tl, step=1, base=time.perf_counter() - 5.0
    )
    # a 0.5s gap is a burst edge: clamped to GAP_CAP_S
    rec2, base2 = _crafted_step(tl, step=2, base=base1 + 0.006 + 0.5)
    assert rec2.host_gap_s == GAP_CAP_S
    # a 2s gap is an idle engine: never host-bound, gap zeroed
    rec3, _ = _crafted_step(tl, step=3, base=base2 + 0.006 + 2.0)
    assert rec3.host_gap_s == 0.0
    # overlap (device_start before previous device_end) is not a gap
    tl2 = StepTimeline()
    _, b1 = _crafted_step(tl2, step=1)
    rec, _ = _crafted_step(tl2, step=2, base=b1 + 0.006 - 0.004 - 0.001)
    assert rec.host_gap_s == 0.0


def test_pure_sync_path_backfills_dispatch():
    """step()-style callers stamp only the wait window; finish backfills
    t_disp1 = t_wait0 so dispatch_s collapses into the decomposition
    without breaking the sum."""
    tl = StepTimeline()
    base = time.perf_counter() - 0.01
    st = _Stamps()
    st.t_enter = base
    st.t_sched = base + 0.001
    st.t_prep = base + 0.002
    st.t_wait0 = base + 0.005
    st.t_wait1 = base + 0.006
    st.sync = True
    prepared = SimpleNamespace(_steptime=st)
    record = tl.finish(
        prepared, step=1, replica=0, kind="prefill", tokens=16,
        fill_ratio=1.0,
    )
    assert record is not None
    assert abs(record.dispatch_s - 0.003) < 1e-9  # t_wait0 - t_prep
    assert abs(sum(record.phases().values()) - record.wall_s) < 1e-9


def test_incomplete_stamps_refuse_to_finish():
    tl = StepTimeline()
    assert tl.finish(
        None, step=1, replica=0, kind="decode", tokens=1, fill_ratio=1.0
    ) is None
    assert tl.finish(
        SimpleNamespace(), step=1, replica=0, kind="decode", tokens=1,
        fill_ratio=1.0,
    ) is None
    st = _Stamps()
    st.t_enter = time.perf_counter()  # everything else missing
    prepared = SimpleNamespace(_steptime=st)
    assert tl.finish(
        prepared, step=1, replica=0, kind="decode", tokens=1,
        fill_ratio=1.0,
    ) is None
    assert len(tl) == 0


# ------------------------------------------------------- ring + reads


def test_ring_bounds_and_window_reads():
    tl = StepTimeline(capacity=4, window=2)
    for step in range(10):
        _live_step(tl, step=step)
    assert len(tl) == 4
    assert [r.step for r in tl.last_records(2)] == [8, 9]
    assert tl.last_records(0) == []
    assert [r["step"] for r in tl.records(last_n=3)] == [7, 8, 9]
    assert len(tl.records()) == 4


def test_host_gap_frac_windowing():
    tl = StepTimeline(window=2)
    _, base1 = _crafted_step(
        tl, step=1, base=time.perf_counter() - 20.0
    )
    _crafted_step(tl, step=2, base=base1 + 0.006 + 0.05)  # gappy
    _crafted_step(tl, step=3, base=base1 + 10.0)          # idle: gap 0
    records = tl.last_records(2)
    expected = sum(r.host_gap_s for r in records) / sum(
        r.wall_s for r in records
    )
    assert abs(tl.host_gap_frac() - expected) < 1e-9
    # window=1 sees only the idle step: no gap at all
    assert tl.host_gap_frac(window=1) == 0.0
    assert StepTimeline().host_gap_frac() == 0.0  # empty ring


def test_record_serializer_and_debug_state_shape():
    tl = StepTimeline()
    record = _live_step(tl)
    as_dict = record.to_dict()
    json.dumps(as_dict)  # wire-ready as-is
    assert set(as_dict["phases"]) == set(PHASES)
    for key in ("step", "replica", "kind", "tokens", "fill_ratio",
                "chained", "sync", "ts", "wall_s", "drain_s",
                "compile_fn"):
        assert key in as_dict
    state = tl.debug_state()
    assert state["steps"] == 1
    assert state["window"] == tl.window
    assert 0.0 <= state["host_gap_frac"] <= 1.0
    assert state["records"] == [as_dict]


def test_anatomy_metrics_observed():
    before = _sample(
        _scrape(), "tgis_tpu_step_anatomy_seconds_count",
        ('phase="device_wait"', 'replica="0"'),
    )
    tl = StepTimeline()
    _live_step(tl)
    after = _sample(
        _scrape(), "tgis_tpu_step_anatomy_seconds_count",
        ('phase="device_wait"', 'replica="0"'),
    )
    assert after - before == 1
    assert "tgis_tpu_host_gap_frac" in _scrape()


# ---------------------------------------------------------- real engine


def _build_engine(tiny_model_dir, **overrides):
    from vllm_tgis_adapter_tpu.engine.async_llm import AsyncLLMEngine
    from vllm_tgis_adapter_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        LoRAConfig,
        ModelConfig,
        ParallelConfig,
        SchedulerConfig,
    )

    mcfg = ModelConfig.from_pretrained(tiny_model_dir, dtype="float32")
    config = EngineConfig(
        model_config=mcfg,
        cache_config=CacheConfig(
            block_size=16, num_blocks=64, cache_dtype=mcfg.dtype
        ),
        scheduler_config=SchedulerConfig(
            max_num_seqs=2, prefill_buckets=(32, 64)
        ),
        parallel_config=ParallelConfig(),
        lora_config=LoRAConfig(),
        **overrides,
    )
    return AsyncLLMEngine.from_config(config)


async def _generate_one(engine, request_id: str, max_tokens: int = 4):
    from vllm_tgis_adapter_tpu.engine.sampling_params import SamplingParams

    final = None
    async for out in engine.generate(
        prompt=None,
        sampling_params=SamplingParams(
            temperature=0.0, max_tokens=max_tokens, ignore_eos=True
        ),
        request_id=request_id,
        prompt_token_ids=list(range(3, 20)),
    ):
        final = out
    return final


def test_engine_populates_step_timeline(tiny_model_dir):
    """A served request leaves finalized StepRecords in the core's
    ring — every one holding the sum invariant — and debug_state()
    carries the step_timeline and doctor sections."""
    engine = _build_engine(tiny_model_dir)

    async def scenario():
        await _generate_one(engine, "steptime-live-1")
        state = engine.debug_state()
        snapshot = engine._stall_snapshot()
        await engine.stop()
        return state, snapshot

    state, snapshot = asyncio.run(scenario())
    json.dumps(state)

    core = engine._replicas[0].engine
    assert len(core.steptime) > 0
    for record in core.steptime.last_records(len(core.steptime)):
        assert abs(sum(record.phases().values()) - record.wall_s) < 1e-9

    (rep_state,) = state["step_timeline"]["replicas"]
    assert rep_state["replica"] == 0
    assert rep_state["steps"] == len(core.steptime)
    assert rep_state["records"]
    kinds = {r["kind"] for r in rep_state["records"]}
    assert kinds <= {"ragged", "solo", "decode-wave"}
    from vllm_tgis_adapter_tpu.telemetry.doctor import REGIMES

    assert state["doctor"]["regimes"] == list(REGIMES)

    # satellite: the watchdog stall snapshot embeds the blamed
    # replica's recent StepRecords for post-mortem anatomy
    blamed = snapshot["stalled_replica"]
    assert blamed["replica"] == 0
    assert blamed["heartbeat_age_s"] >= 0
    assert blamed["step_records"] == core.steptime.records(last_n=64)


# ---------------------------------------------------------- chrome trace


def test_chrome_trace_golden_shape(tiny_model_dir):
    from vllm_tgis_adapter_tpu.telemetry.timeline import (
        DOCTOR_TID,
        EVENTS_TID,
        LEDGER_TID,
        PHASE_TIDS,
        chrome_trace_from_state,
        chrome_trace_json,
    )

    engine = _build_engine(tiny_model_dir)

    async def scenario():
        await _generate_one(engine, "timeline-1")
        state = engine.debug_state()
        await engine.stop()
        return state

    state = asyncio.run(scenario())
    trace = chrome_trace_from_state(state)
    json.dumps(trace)  # valid JSON end to end
    events = trace["traceEvents"]
    assert events

    meta = [e for e in events if e["ph"] == "M"]
    spans = [e for e in events if e["ph"] != "M"]
    assert meta and spans
    # metadata names every fixed track for every replica pid
    named = {(e["pid"], e.get("tid")) for e in meta}
    for pid in trace["otherData"]["replicas"]:
        assert (pid, None) in named
        for tid in (*PHASE_TIDS.values(), EVENTS_TID, DOCTOR_TID,
                    LEDGER_TID):
            assert (pid, tid) in named

    # non-meta events are ts-sorted (Perfetto does not require it, but
    # the exporter promises it so saved traces diff cleanly)
    stamps = [e["ts"] for e in spans]
    assert stamps == sorted(stamps)

    # stable pid/tid mapping: step phases on tracks 1-6 of the
    # replica's process, recorder instants on the fixed events track
    phase_spans = [e for e in spans if e.get("cat") == "step"]
    assert phase_spans
    for span in phase_spans:
        assert span["pid"] == 0
        assert span["tid"] == PHASE_TIDS[span["name"]]
        assert span["dur"] >= 1
    recorder_marks = [e for e in spans if e.get("cat") == "recorder"]
    assert recorder_marks
    assert all(e["tid"] == EVENTS_TID for e in recorder_marks)
    kinds = {e["name"] for e in recorder_marks}
    assert "admit" in kinds and "finish" in kinds

    # the serialized form all three surfaces serve round-trips
    assert json.loads(chrome_trace_json(state, last_steps=2))[
        "traceEvents"
    ]


def test_chrome_trace_ledger_and_doctor_tracks():
    """Offline composition: doctor episodes and --ledger-log records
    land on their fixed tracks with bounded durations."""
    from vllm_tgis_adapter_tpu.telemetry.timeline import (
        DOCTOR_TID,
        LEDGER_TID,
        chrome_trace_from_state,
    )

    state = {
        "step_timeline": {"replicas": []},
        "events": [],
        "doctor": {
            "active": [],
            "recent": [{
                "regime": "host_bound", "replica": 1,
                "opened_ts": 100.0, "closed_ts": 103.5,
                "evidence": {"host_gap_frac": 0.6}, "captured": True,
            }],
        },
    }
    ledger = [
        {"request_id": "r1", "arrival_time": 99.0, "queue_s": 0.5,
         "prefill_s": 0.2, "decode_s": 1.3, "outcome": "finish",
         "tenant": "t", "request_class": "default",
         "tokens_in": 16, "tokens_out": 4},
        {"request_id": "skipped-no-arrival"},
    ]
    trace = chrome_trace_from_state(state, ledger_records=ledger)
    spans = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    (doc,) = [e for e in spans if e["cat"] == "doctor"]
    assert doc["name"] == "host_bound"
    assert doc["pid"] == 1 and doc["tid"] == DOCTOR_TID
    assert doc["dur"] == 3_500_000  # 3.5s in chrome-trace microseconds
    assert doc["args"]["captured"] is True and doc["args"]["open"] is False
    (req,) = [e for e in spans if e["cat"] == "ledger"]
    assert req["tid"] == LEDGER_TID and req["name"] == "finish"
    assert req["dur"] == 2_000_000


# --------------------------------------------------------- HTTP surfaces


def _debug_app(engine, tiny_model_dir):
    import argparse

    from vllm_tgis_adapter_tpu.http import build_http_server

    args = argparse.Namespace(
        served_model_name=None, model=tiny_model_dir, api_key=None,
        root_path=None, profile_dir=None,
    )
    return build_http_server(args, engine)


def test_http_section_filter_doctor_and_timeline(tiny_model_dir):
    from vllm_tgis_adapter_tpu.http import HttpRequest

    engine = _build_engine(tiny_model_dir)
    app = _debug_app(engine, tiny_model_dir)

    def _get(path):
        return app.dispatch(HttpRequest("GET", path, {}, b""))

    async def scenario():
        await _generate_one(engine, "http-steptime-1")
        responses = {
            "section": await _get(
                "/debug/state?section=step_timeline,doctor"
            ),
            "bad_section": await _get("/debug/state?section=bogus"),
            "doctor": await _get("/debug/doctor"),
            "timeline": await _get("/debug/timeline?format=chrome"),
            "timeline_default": await _get("/debug/timeline"),
            "bad_format": await _get("/debug/timeline?format=xml"),
            "bad_last": await _get(
                "/debug/timeline?format=chrome&last_steps=zap"
            ),
            "bounded": await _get(
                "/debug/timeline?format=chrome&last_steps=1"
            ),
        }
        await engine.stop()
        return responses

    r = asyncio.run(scenario())

    assert r["section"].status == 200
    section = json.loads(r["section"].body)
    assert set(section) == {"step_timeline", "doctor"}
    assert section["step_timeline"]["replicas"][0]["records"]

    assert r["bad_section"].status == 404
    assert "bogus" in json.loads(r["bad_section"].body)["error"]["message"]

    assert r["doctor"].status == 200
    doctor = json.loads(r["doctor"].body)
    assert doctor["regimes"] and "thresholds" in doctor

    for key in ("timeline", "timeline_default", "bounded"):
        assert r[key].status == 200
        trace = json.loads(r[key].body)
        assert any(e["ph"] == "M" for e in trace["traceEvents"])
    assert r["bad_format"].status == 400
    assert r["bad_last"].status == 400

    # bounded export carries at most 1 step's phase spans per replica
    bounded = json.loads(r["bounded"].body)["traceEvents"]
    steps = {
        e["args"]["step"] for e in bounded
        if e["ph"] == "X" and e.get("cat") == "step"
    }
    assert len(steps) <= 1
