"""Test session setup.

Force the JAX CPU backend with 8 virtual devices so the whole suite
(including SPMD mesh tests) runs on CPU-only CI — the capability the
reference lacks entirely (its CI compiles vLLM for CPU but has no
distributed tests, SURVEY.md §4).

The host environment may import jax at interpreter startup (sitecustomize
registering a TPU PJRT plugin) with JAX_PLATFORMS pointing at real
hardware; by then env vars are already read, so the platform override must
go through ``jax.config`` — but XLA_FLAGS is still read lazily at backend
initialisation, so it must be set before the first device query.
"""

from __future__ import annotations

import os
from pathlib import Path

# RUN_TPU_TESTS=1 keeps the real backend so `pytest -m tpu` compiles the
# Pallas kernels through Mosaic on hardware (tests/test_tpu_kernels.py) —
# the gate that interpreter-mode parity structurally cannot provide
_TPU_RUN = os.environ.get("RUN_TPU_TESTS") == "1"

# Step-boundary invariant sanitizer (engine/sanitizer.py): on for the
# WHOLE tier-1 suite, so every existing test doubles as an invariant
# test over allocator/arena/tier/pool accounting.  setdefault so a
# developer can still run with TGIS_TPU_SANITIZE=0 to bisect whether a
# failure is the bug itself or the sanitizer tripping on it.
os.environ.setdefault("TGIS_TPU_SANITIZE", "1")

if not _TPU_RUN:
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import jax  # noqa: E402

if not _TPU_RUN:
    jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def tiny_model_dir(tmp_path_factory) -> str:
    """A tiny randomly-initialised llama-style model + tokenizer on disk."""
    from tests.fixture_models import build_tiny_llama

    path = tmp_path_factory.mktemp("tiny-llama")
    build_tiny_llama(str(path))
    return str(path)


def _build_args(argv: list[str]):
    """Run the REAL parser chain, as the reference's conftest does
    (conftest.py:80-98), instead of constructing a namespace by hand."""
    import sys

    from vllm_tgis_adapter_tpu.tgis_utils.args import (
        make_parser,
        postprocess_tgis_args,
    )

    old_argv = sys.argv
    sys.argv = ["__main__.py", *argv]
    try:
        return postprocess_tgis_args(make_parser().parse_args())
    finally:
        sys.argv = old_argv


@pytest.fixture(scope="session")
def adapter_cache_dir(tmp_path_factory) -> str:
    """Adapter cache with one real tiny-llama LoRA fixture + one non-LoRA
    peft dir (exercised as the unsupported-type path, like the
    reference's bloomz prompt-tuning fixture)."""
    import json

    from tests.fixture_models import build_tiny_lora_adapter

    cache = tmp_path_factory.mktemp("adapters")
    build_tiny_lora_adapter(str(cache / "tiny-lora"))
    prompt_dir = cache / "tiny-prompt-adapter"
    prompt_dir.mkdir()
    json.dump({"peft_type": "PROMPT_TUNING"},
              open(prompt_dir / "adapter_config.json", "w"))
    return str(cache)


def _require_pb() -> None:
    """Skip (don't error) when the protoc-generated gRPC bindings are
    unavailable: the pb package probes every pb2 module at import and
    regenerates stale ones, which needs protoc on PATH."""
    try:  # pragma: no cover - environment probe
        import vllm_tgis_adapter_tpu.grpc.pb  # noqa: F401
    except ImportError as e:
        pytest.skip(
            f"protoc-generated gRPC bindings unavailable ({e}); install "
            "protoc (or a wheel with prebuilt pb2 modules) to run the "
            "dual-server suites"
        )


@pytest.fixture(scope="session")
def server_args(tiny_model_dir, adapter_cache_dir):
    _require_pb()
    from tests.utils import get_random_port

    return _build_args(
        [
            "--model",
            tiny_model_dir,
            "--max-model-len",
            "512",
            "--dtype",
            "float32",
            "--grpc-port",
            str(get_random_port()),
            "--port",
            str(get_random_port()),
            "--max-num-seqs",
            "8",
            "--adapter-cache",
            adapter_cache_dir,
        ]
    )


@pytest.fixture(scope="session")
def _servers(server_args):
    """Boot the REAL dual-server stack (no mock engine) in a background
    thread's event loop, mirroring the reference's integration strategy."""
    _require_pb()
    import asyncio
    import threading
    import urllib.request
    from contextlib import suppress

    from tests.utils import GrpcClient, wait_until

    from vllm_tgis_adapter_tpu.__main__ import start_servers

    loop = asyncio.new_event_loop()
    server_task = None

    def target() -> None:
        nonlocal server_task
        asyncio.set_event_loop(loop)
        server_task = loop.create_task(start_servers(server_args))
        with suppress(asyncio.CancelledError):
            loop.run_until_complete(server_task)

    thread = threading.Thread(target=target, daemon=True)
    thread.start()

    def http_healthy() -> bool:
        with urllib.request.urlopen(
            f"http://localhost:{server_args.port}/health", timeout=5
        ) as resp:
            return resp.status == 200

    def grpc_healthy() -> bool:
        with GrpcClient("localhost", server_args.grpc_port) as client:
            return client.health_check()

    try:
        wait_until(http_healthy, timeout=300)
        wait_until(grpc_healthy, timeout=60)
        yield server_args
    finally:

        def cancel_all() -> None:
            for task in asyncio.all_tasks(loop):
                task.cancel()

        loop.call_soon_threadsafe(cancel_all)
        thread.join(timeout=60)
        if not loop.is_closed():
            loop.close()


@pytest.fixture
def grpc_client(_servers):
    from tests.utils import GrpcClient

    with GrpcClient("localhost", _servers.grpc_port) as client:
        yield client


@pytest.fixture
def http_base_url(_servers) -> str:
    return f"http://localhost:{_servers.port}"
