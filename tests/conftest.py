"""Test session setup.

Force the JAX CPU backend with 8 virtual devices so the whole suite
(including SPMD mesh tests) runs on CPU-only CI — the capability the
reference lacks entirely (its CI compiles vLLM for CPU but has no
distributed tests, SURVEY.md §4).

The host environment may import jax at interpreter startup (sitecustomize
registering a TPU PJRT plugin) with JAX_PLATFORMS pointing at real
hardware; by then env vars are already read, so the platform override must
go through ``jax.config`` — but XLA_FLAGS is still read lazily at backend
initialisation, so it must be set before the first device query.
"""

from __future__ import annotations

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def tiny_model_dir(tmp_path_factory) -> str:
    """A tiny randomly-initialised llama-style model + tokenizer on disk."""
    from tests.fixture_models import build_tiny_llama

    path = tmp_path_factory.mktemp("tiny-llama")
    build_tiny_llama(str(path))
    return str(path)
