"""Chained decode waves (vLLM-style async scheduling).

While one fused decode wave executes on device, its successor is planned
from host projections and dispatched with token feedback read from the
in-flight wave's device outputs (engine/runner.py chained_decode_steps).
Pinned here:

* greedy output parity with the synchronous engine;
* rows finishing early (EOS/max_tokens) mid-chain discard the successor
  wave's tokens without corrupting batchmates;
* abort while a chained wave is in flight;
* the free-quarantine epochs that keep stale projected writes off
  re-allocated pages.
"""

from __future__ import annotations

import asyncio

import pytest


def _config(tiny_model_dir, **sched):
    from vllm_tgis_adapter_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        LoRAConfig,
        ModelConfig,
        ParallelConfig,
        SchedulerConfig,
    )

    mcfg = ModelConfig.from_pretrained(tiny_model_dir, dtype="float32")
    return EngineConfig(
        model_config=mcfg,
        cache_config=CacheConfig(block_size=16, num_blocks=64,
                                 cache_dtype=mcfg.dtype),
        scheduler_config=SchedulerConfig(
            max_num_seqs=sched.pop("max_num_seqs", 4),
            prefill_buckets=(32,),
            num_decode_steps=sched.pop("num_decode_steps", 4), **sched),
        parallel_config=ParallelConfig(),
        lora_config=LoRAConfig(),
    )


def _sync_baseline(config, requests):
    from vllm_tgis_adapter_tpu.engine.core import LLMEngine
    from vllm_tgis_adapter_tpu.engine.sampling_params import SamplingParams

    engine = LLMEngine.from_config(config)
    for rid, ids, kwargs in requests:
        engine.add_request(rid, None, SamplingParams(**kwargs),
                           prompt_token_ids=ids)
    outs = {}
    for _ in range(400):
        if not engine.has_unfinished_requests():
            break
        for out in engine.step():
            if out.finished:
                outs[out.request_id] = out
    return {rid: o.outputs[0].token_ids for rid, o in outs.items()}


def _async_run(config, requests, expect_chained=True):
    from vllm_tgis_adapter_tpu.engine.async_llm import AsyncLLMEngine
    from vllm_tgis_adapter_tpu.engine.core import LLMEngine
    from vllm_tgis_adapter_tpu.engine.sampling_params import SamplingParams

    async def scenario():
        core = LLMEngine.from_config(config)
        engine = AsyncLLMEngine(core)
        chained_count = [0]
        inner = core.dispatch_chained_step

        def spy(plan, prepared, prev_handle):
            chained_count[0] += 1
            return inner(plan, prepared, prev_handle)

        core.dispatch_chained_step = spy
        results = {}

        async def one(rid, ids, kwargs):
            final = None
            async for out in engine.generate(
                prompt=None,
                sampling_params=SamplingParams(**kwargs),
                request_id=rid,
                prompt_token_ids=ids,
            ):
                final = out
            results[rid] = final.outputs[0].token_ids

        await asyncio.gather(
            *[one(rid, ids, kw) for rid, ids, kw in requests]
        )
        await engine.stop()
        return results, chained_count[0]

    results, chained = asyncio.run(scenario())
    if expect_chained:
        assert chained > 0, "no chained decode wave was dispatched"
    return results


def test_chained_greedy_matches_sync(tiny_model_dir):
    """Long greedy generations (many waves) must be token-identical to
    the synchronous engine, and chained dispatches must actually fire."""
    requests = [
        ("a", list(range(3, 10)),
         dict(temperature=0.0, max_tokens=32, ignore_eos=True)),
        ("b", list(range(11, 20)),
         dict(temperature=0.0, max_tokens=32, ignore_eos=True)),
    ]
    baseline = _sync_baseline(_config(tiny_model_dir), requests)
    chained = _async_run(_config(tiny_model_dir), requests)
    assert chained == baseline


def test_chained_seeded_sampling_matches_sync(tiny_model_dir):
    """Chained waves keep the position-based PRNG streams: a seeded
    sampled request produces the identical tokens as the sync engine."""
    requests = [
        ("s", list(range(3, 10)),
         dict(temperature=0.9, seed=11, max_tokens=24, ignore_eos=True)),
    ]
    baseline = _sync_baseline(_config(tiny_model_dir), requests)
    chained = _async_run(_config(tiny_model_dir), requests)
    assert chained == baseline


def test_chained_mixed_lengths_early_finish(tiny_model_dir):
    """A row hitting max_tokens mid-chain discards its projected wave
    tokens; surviving batchmates stay token-identical to sync."""
    requests = [
        ("short", list(range(3, 10)),
         dict(temperature=0.0, max_tokens=6, ignore_eos=True)),
        ("long", list(range(11, 20)),
         dict(temperature=0.0, max_tokens=40, ignore_eos=True)),
    ]
    baseline = _sync_baseline(_config(tiny_model_dir), requests)
    chained = _async_run(_config(tiny_model_dir), requests)
    assert chained == baseline
    assert len(chained["short"]) == 6
    assert len(chained["long"]) == 40


def test_abort_during_chained_flight(tiny_model_dir):
    """abort() landing while a chained wave is in flight cancels the
    request; its packmate completes identically to sync."""
    from vllm_tgis_adapter_tpu.engine.async_llm import AsyncLLMEngine
    from vllm_tgis_adapter_tpu.engine.core import LLMEngine
    from vllm_tgis_adapter_tpu.engine.sampling_params import (
        RequestOutputKind,
        SamplingParams,
    )

    config = _config(tiny_model_dir)

    async def scenario():
        core = LLMEngine.from_config(config)
        engine = AsyncLLMEngine(core)
        chained_seen = asyncio.Event()
        inner = core.dispatch_chained_step

        def spy(plan, prepared, prev_handle):
            chained_seen.set()
            return inner(plan, prepared, prev_handle)

        core.dispatch_chained_step = spy

        outs = {}

        async def one(rid, max_tokens):
            final = None
            produced = 0
            async for out in engine.generate(
                prompt=None,
                sampling_params=SamplingParams(
                    temperature=0.0, max_tokens=max_tokens,
                    ignore_eos=True,
                    output_kind=RequestOutputKind.DELTA),
                request_id=rid,
                prompt_token_ids=list(range(3, 10)),
            ):
                final = out
                produced += len(out.outputs[0].token_ids)
            outs[rid] = (final, produced)

        tasks = [
            asyncio.create_task(one("victim", 64)),
            asyncio.create_task(one("survivor", 64)),
        ]
        await asyncio.wait_for(chained_seen.wait(), timeout=30)
        await engine.abort("victim")
        await asyncio.wait_for(asyncio.gather(*tasks), timeout=60)
        await engine.stop()
        # pool fully reclaimable once everything finished (quarantine
        # epochs all flushed)
        alloc = core.scheduler.allocator
        assert not alloc._free_epochs
        assert alloc.num_free == alloc.num_blocks
        return outs

    outs = asyncio.run(scenario())
    assert outs["victim"][0].outputs[0].finish_reason == "abort"
    assert outs["survivor"][0].outputs[0].finish_reason == "length"
    assert outs["survivor"][1] == 64


def test_free_epoch_quarantine_unit():
    """free() during an open epoch buffers; pages release only at the
    matching flush, in FIFO epoch order."""
    from vllm_tgis_adapter_tpu.engine.kv_cache import BlockAllocator

    alloc = BlockAllocator(8, 16)
    a = alloc.allocate(2)
    b = alloc.allocate(2)
    assert alloc.num_free == 4

    alloc.begin_free_epoch()
    alloc.free(a)
    assert alloc.num_free == 4  # quarantined, not reusable
    alloc.begin_free_epoch()
    alloc.free(b)
    assert alloc.num_free == 4

    alloc.flush_free_epoch()  # oldest epoch: releases a
    assert alloc.num_free == 6
    alloc.flush_free_epoch()
    assert alloc.num_free == 8
    # balanced: no epochs left, frees are immediate again
    c = alloc.allocate(1)
    alloc.free(c)
    assert alloc.num_free == 8


def test_engine_death_during_chained_wave_flushes_epochs(tiny_model_dir):
    """A chained dispatch failure is whole-engine death (crash-fast):
    consumers get the error, and the quarantine epochs flush at loop
    teardown so no pages leak."""
    from vllm_tgis_adapter_tpu.engine.async_llm import AsyncLLMEngine
    from vllm_tgis_adapter_tpu.engine.core import LLMEngine
    from vllm_tgis_adapter_tpu.engine.sampling_params import SamplingParams

    config = _config(tiny_model_dir)

    async def scenario():
        core = LLMEngine.from_config(config)
        engine = AsyncLLMEngine(core)

        def boom(plan, prepared, prev_handle):
            raise RuntimeError("injected chained-dispatch failure")

        core.dispatch_chained_step = boom

        with pytest.raises(RuntimeError, match="injected"):
            async for _ in engine.generate(
                prompt=None,
                sampling_params=SamplingParams(
                    temperature=0.0, max_tokens=32, ignore_eos=True),
                request_id="doomed",
                prompt_token_ids=list(range(3, 10)),
            ):
                pass
        assert engine.errored
        assert not core.scheduler.allocator._free_epochs
        await engine.stop()

    asyncio.run(scenario())


def test_chained_engages_under_saturation(tiny_model_dir):
    """A full batch with a waiting queue BLOCKED on slots must still
    chain (the saturated-server steady state): before round 5 the
    scheduler bailed on ANY waiting work, so a loaded server never got
    on-device token feedback.  Outputs stay token-identical to sync and
    every queued request completes."""
    requests = [
        (f"r{i}", list(range(3 + i, 12 + i)),
         dict(temperature=0.0, max_tokens=24, ignore_eos=True))
        for i in range(5)
    ]
    # max_num_seqs=2 -> 2 running, 3 waiting with no free slot for the
    # whole first cohort; admissions happen only as rows finish
    config = _config(tiny_model_dir, max_num_seqs=2)
    baseline = _sync_baseline(config, requests)
    chained = _async_run(_config(tiny_model_dir, max_num_seqs=2), requests)
    assert chained == baseline
    assert all(len(v) == 24 for v in chained.values())


def test_waiting_head_admissible_predicate(tiny_model_dir):
    """Unit: the chain gate mirrors admission — blocked on slots or
    pages -> not admissible (chain allowed); resources free ->
    admissible (chain bails)."""
    from vllm_tgis_adapter_tpu.engine.core import LLMEngine
    from vllm_tgis_adapter_tpu.engine.sampling_params import SamplingParams

    config = _config(tiny_model_dir, max_num_seqs=2)
    engine = LLMEngine.from_config(config)
    sched = engine.scheduler
    assert not sched._waiting_head_admissible()  # empty queue

    for rid in ("a", "b", "c"):
        engine.add_request(rid, None,
                           SamplingParams(temperature=0.0, max_tokens=8,
                                          ignore_eos=True),
                           prompt_token_ids=list(range(3, 10)))
    # nothing admitted yet: head is admissible (slots + pages free)
    assert sched._waiting_head_admissible()
    # admit a+b (fills both slots) -> head "c" blocked on slots
    for _ in range(4):
        if len(sched.running) == 2:
            break
        engine.step()
    assert len(sched.running) == 2
    assert sched.waiting and sched.waiting[0].request_id == "c"
    assert not sched._free_slots
    assert not sched._waiting_head_admissible()


def test_admissible_probe_releases_prefix_refcounts(tiny_model_dir):
    """The chain-gate's prefix probe must not pin cached pages: repeated
    probes with prefix caching on leave the allocator's free count
    untouched (match_prefix refcounts its hits; the probe frees them)."""
    from vllm_tgis_adapter_tpu.engine.core import LLMEngine
    from vllm_tgis_adapter_tpu.engine.sampling_params import SamplingParams

    import dataclasses as _dc

    config = _config(tiny_model_dir, max_num_seqs=2)
    config = _dc.replace(
        config,
        cache_config=_dc.replace(config.cache_config,
                                 enable_prefix_caching=True))
    engine = LLMEngine.from_config(config)
    sched = engine.scheduler

    shared = list(range(3, 35))  # two full pages of shared prefix
    engine.add_request("warm", None,
                       SamplingParams(temperature=0.0, max_tokens=4,
                                      ignore_eos=True),
                       prompt_token_ids=shared)
    for _ in range(40):
        if not engine.has_unfinished_requests():
            break
        engine.step()
    free_before = sched.allocator.num_free

    # same prefix waits in the queue: every probe hits the cache
    engine.add_request("probe-target", None,
                       SamplingParams(temperature=0.0, max_tokens=4,
                                      ignore_eos=True),
                       prompt_token_ids=list(shared))
    for _ in range(25):
        sched._waiting_head_admissible()
    assert sched.allocator.num_free == free_before
