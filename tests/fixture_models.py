"""Deterministic tiny-model fixtures built offline (no network).

The reference's tests boot a real tiny HF model downloaded from the hub
(tests/conftest.py:85-89 in the reference); this environment has no network
egress, so we synthesise an equivalent: a 2-layer llama-architecture
checkpoint with a from-scratch byte-level BPE tokenizer, saved in standard
HF format so the whole load path (config.json → safetensors → tokenizer) is
exercised for real.
"""

from __future__ import annotations

import json
from pathlib import Path

_CORPUS = [
    "the quick brown fox jumps over the lazy dog",
    "hello world, this is a tiny test corpus for a tiny tokenizer",
    "The capital of France is Paris. The capital of Italy is Rome.",
    "0 1 2 3 4 5 6 7 8 9 10 11 12 13 14 15 16 17 18 19 20",
    "def main():\n    print('hello')\n    return 0\n",
    '{"name": "value", "list": [1, 2, 3], "flag": true}',
    "to be or not to be, that is the question",
    "pack my box with five dozen liquor jugs",
]

TINY_LLAMA_CONFIG = {
    "architectures": ["LlamaForCausalLM"],
    "model_type": "llama",
    "vocab_size": 512,
    "hidden_size": 64,
    "intermediate_size": 128,
    "num_hidden_layers": 2,
    "num_attention_heads": 4,
    "num_key_value_heads": 2,
    "head_dim": 16,
    "max_position_embeddings": 512,
    "rope_theta": 10000.0,
    "rms_norm_eps": 1e-6,
    "tie_word_embeddings": False,
    "bos_token_id": 1,
    "eos_token_id": 2,
    "torch_dtype": "float32",
}


def hf_reference_model(model_dir: str, **kwargs):
    """Torch-side gold reference for numerical-parity tests (shared by
    test_model_correctness / test_opt / test_gpt_neox / sliding-window
    so HF loading settings cannot silently diverge between families).
    kwargs pass through (e.g. attn_implementation='eager', which the
    sliding-window tests need for HF to honor the band mask)."""
    import torch
    from transformers import AutoModelForCausalLM

    hf = AutoModelForCausalLM.from_pretrained(
        model_dir, torch_dtype=torch.float32, **kwargs
    )
    hf.eval()
    return hf


def build_tiny_mistral(path: str, seed: int = 0,
                       sliding_window: int | None = 8) -> str:
    """Tiny mistral-architecture checkpoint: llama tensor naming with a
    sliding-window config (the v0.1 lineage's distinguishing feature)."""
    build_tiny_llama(path, seed=seed)
    cfg = json.load(open(Path(path) / "config.json"))
    cfg["architectures"] = ["MistralForCausalLM"]
    cfg["model_type"] = "mistral"
    cfg["sliding_window"] = sliding_window
    with open(Path(path) / "config.json", "w") as f:
        json.dump(cfg, f, indent=2)
    return path


def hf_tokenize(model_dir: str, text: str) -> list:
    from transformers import AutoTokenizer

    return AutoTokenizer.from_pretrained(model_dir)(text).input_ids


def build_tokenizer(path: str, vocab_size: int = 512):
    from tokenizers import Tokenizer, decoders, models, pre_tokenizers, trainers
    from transformers import PreTrainedTokenizerFast

    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=vocab_size,
        special_tokens=["<unk>", "<s>", "</s>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
        show_progress=False,
    )
    tok.train_from_iterator(_CORPUS, trainer=trainer)
    fast = PreTrainedTokenizerFast(
        tokenizer_object=tok,
        unk_token="<unk>",
        bos_token="<s>",
        eos_token="</s>",
        pad_token="</s>",
    )
    # pad the vocab to exactly vocab_size so every model id round-trips
    # through convert_ids_to_tokens (BPE training on the tiny corpus stops
    # short of the requested size)
    n_missing = vocab_size - len(fast)
    if n_missing > 0:
        fast.add_tokens([f"<filler_{i}>" for i in range(n_missing)])
    fast.save_pretrained(path)
    return fast


def write_llama_safetensors(path: str, *, vocab_size: int,
                            hidden_size: int, intermediate_size: int,
                            num_layers: int, num_heads: int,
                            num_kv_heads: int, head_dim: int,
                            seed: int = 0) -> None:
    """HF-format llama ``model.safetensors`` with seed-deterministic
    random weights, shaped by the given arch — the single source of the
    tensor-name layout the loader expects (engine/weights.py).  The
    tiny test fixture and bench.py's dp-fleet model both write through
    here so the layout cannot drift between them."""
    import numpy as np
    from safetensors.numpy import save_file

    rng = np.random.default_rng(seed)
    d = hidden_size
    dh = head_dim

    def w(shape):
        return (rng.standard_normal(shape) * 0.02).astype(np.float32)

    tensors = {
        "model.embed_tokens.weight": w((vocab_size, d)),
        "model.norm.weight": np.ones(d, dtype=np.float32),
        "lm_head.weight": w((vocab_size, d)),
    }
    for i in range(num_layers):
        p = f"model.layers.{i}"
        tensors |= {
            f"{p}.input_layernorm.weight": np.ones(d, dtype=np.float32),
            f"{p}.post_attention_layernorm.weight": np.ones(d, dtype=np.float32),
            f"{p}.self_attn.q_proj.weight": w((num_heads * dh, d)),
            f"{p}.self_attn.k_proj.weight": w((num_kv_heads * dh, d)),
            f"{p}.self_attn.v_proj.weight": w((num_kv_heads * dh, d)),
            f"{p}.self_attn.o_proj.weight": w((d, num_heads * dh)),
            f"{p}.mlp.gate_proj.weight": w((intermediate_size, d)),
            f"{p}.mlp.up_proj.weight": w((intermediate_size, d)),
            f"{p}.mlp.down_proj.weight": w((d, intermediate_size)),
        }
    save_file(tensors, Path(path) / "model.safetensors")


def build_tiny_llama(path: str, seed: int = 0) -> str:
    """Write config.json + model.safetensors + tokenizer to ``path``."""
    out = Path(path)
    out.mkdir(parents=True, exist_ok=True)

    tokenizer = build_tokenizer(path)
    cfg = dict(TINY_LLAMA_CONFIG)
    cfg["vocab_size"] = max(cfg["vocab_size"], len(tokenizer))
    with open(out / "config.json", "w") as f:
        json.dump(cfg, f, indent=2)

    write_llama_safetensors(
        path,
        vocab_size=cfg["vocab_size"],
        hidden_size=cfg["hidden_size"],
        intermediate_size=cfg["intermediate_size"],
        num_layers=cfg["num_hidden_layers"],
        num_heads=cfg["num_attention_heads"],
        num_kv_heads=cfg["num_key_value_heads"],
        head_dim=cfg["head_dim"],
        seed=seed,
    )
    return str(out)


def build_tiny_mixtral(path: str, seed: int = 0, num_experts: int = 4,
                       experts_per_tok: int = 2) -> str:
    """Tiny mixtral-architecture checkpoint: llama attention skeleton with
    a router + per-expert FFNs in HF block_sparse_moe naming."""
    import numpy as np
    from safetensors.numpy import save_file

    out = Path(path)
    out.mkdir(parents=True, exist_ok=True)

    tokenizer = build_tokenizer(path)
    cfg = dict(TINY_LLAMA_CONFIG)
    cfg["architectures"] = ["MixtralForCausalLM"]
    cfg["model_type"] = "mixtral"
    cfg["num_local_experts"] = num_experts
    cfg["num_experts_per_tok"] = experts_per_tok
    cfg["vocab_size"] = max(cfg["vocab_size"], len(tokenizer))
    with open(out / "config.json", "w") as f:
        json.dump(cfg, f, indent=2)

    rng = np.random.default_rng(seed)
    d = cfg["hidden_size"]
    dh = cfg["head_dim"]
    h = cfg["num_attention_heads"]
    hkv = cfg["num_key_value_heads"]
    inter = cfg["intermediate_size"]
    vocab = cfg["vocab_size"]

    def w(shape):
        return (rng.standard_normal(shape) * 0.02).astype(np.float32)

    tensors = {
        "model.embed_tokens.weight": w((vocab, d)),
        "model.norm.weight": np.ones(d, dtype=np.float32),
        "lm_head.weight": w((vocab, d)),
    }
    for i in range(cfg["num_hidden_layers"]):
        p = f"model.layers.{i}"
        tensors |= {
            f"{p}.input_layernorm.weight": np.ones(d, dtype=np.float32),
            f"{p}.post_attention_layernorm.weight": np.ones(d, dtype=np.float32),
            f"{p}.self_attn.q_proj.weight": w((h * dh, d)),
            f"{p}.self_attn.k_proj.weight": w((hkv * dh, d)),
            f"{p}.self_attn.v_proj.weight": w((hkv * dh, d)),
            f"{p}.self_attn.o_proj.weight": w((d, h * dh)),
            f"{p}.block_sparse_moe.gate.weight": w((num_experts, d)),
        }
        for e in range(num_experts):
            q = f"{p}.block_sparse_moe.experts.{e}"
            tensors |= {
                f"{q}.w1.weight": w((inter, d)),
                f"{q}.w2.weight": w((d, inter)),
                f"{q}.w3.weight": w((inter, d)),
            }
    save_file(tensors, out / "model.safetensors")
    return str(out)


TINY_OPT_CONFIG = {
    "architectures": ["OPTForCausalLM"],
    "model_type": "opt",
    "vocab_size": 512,
    "hidden_size": 64,
    "ffn_dim": 128,
    "num_hidden_layers": 2,
    "num_attention_heads": 4,
    "max_position_embeddings": 512,
    "word_embed_proj_dim": 64,
    "do_layer_norm_before": True,
    "enable_bias": True,
    "activation_function": "relu",
    "tie_word_embeddings": True,
    "bos_token_id": 1,
    "eos_token_id": 2,
    "pad_token_id": 2,
    "torch_dtype": "float32",
}


def build_tiny_opt(path: str, seed: int = 0) -> str:
    """Tiny OPT-architecture checkpoint in HF naming (BASELINE.json's
    opt-125m config class): learned offset-by-2 positions, pre-LayerNorm
    with biases, fc1/ReLU/fc2, tied lm_head."""
    import numpy as np
    from safetensors.numpy import save_file

    out = Path(path)
    out.mkdir(parents=True, exist_ok=True)

    tokenizer = build_tokenizer(path)
    cfg = dict(TINY_OPT_CONFIG)
    cfg["vocab_size"] = max(cfg["vocab_size"], len(tokenizer))
    with open(out / "config.json", "w") as f:
        json.dump(cfg, f, indent=2)

    rng = np.random.default_rng(seed)
    d = cfg["hidden_size"]
    h = cfg["num_attention_heads"]
    inter = cfg["ffn_dim"]
    vocab = cfg["vocab_size"]

    def w(shape):
        return (rng.standard_normal(shape) * 0.02).astype(np.float32)

    def b(n):
        return (rng.standard_normal(n) * 0.01).astype(np.float32)

    tensors = {
        "model.decoder.embed_tokens.weight": w((vocab, d)),
        "model.decoder.embed_positions.weight": w(
            (cfg["max_position_embeddings"] + 2, d)
        ),
        "model.decoder.final_layer_norm.weight": np.ones(d, np.float32),
        "model.decoder.final_layer_norm.bias": b(d),
    }
    for i in range(cfg["num_hidden_layers"]):
        p = f"model.decoder.layers.{i}"
        tensors |= {
            f"{p}.self_attn_layer_norm.weight": np.ones(d, np.float32),
            f"{p}.self_attn_layer_norm.bias": b(d),
            f"{p}.final_layer_norm.weight": np.ones(d, np.float32),
            f"{p}.final_layer_norm.bias": b(d),
            f"{p}.self_attn.q_proj.weight": w((d, d)),
            f"{p}.self_attn.q_proj.bias": b(d),
            f"{p}.self_attn.k_proj.weight": w((d, d)),
            f"{p}.self_attn.k_proj.bias": b(d),
            f"{p}.self_attn.v_proj.weight": w((d, d)),
            f"{p}.self_attn.v_proj.bias": b(d),
            f"{p}.self_attn.out_proj.weight": w((d, d)),
            f"{p}.self_attn.out_proj.bias": b(d),
            f"{p}.fc1.weight": w((inter, d)),
            f"{p}.fc1.bias": b(inter),
            f"{p}.fc2.weight": w((d, inter)),
            f"{p}.fc2.bias": b(d),
        }
    save_file(tensors, out / "model.safetensors")
    return str(out)


TINY_GPT_NEOX_CONFIG = {
    "architectures": ["GPTNeoXForCausalLM"],
    "model_type": "gpt_neox",
    "vocab_size": 512,
    "hidden_size": 64,
    "intermediate_size": 128,
    "num_hidden_layers": 2,
    "num_attention_heads": 4,
    "max_position_embeddings": 512,
    "rotary_pct": 0.25,
    "rotary_emb_base": 10000,
    "layer_norm_eps": 1e-5,
    "use_parallel_residual": True,
    "hidden_act": "gelu",
    "tie_word_embeddings": False,
    "bos_token_id": 1,
    "eos_token_id": 2,
    "torch_dtype": "float32",
}


def build_tiny_gpt_neox(path: str, seed: int = 0) -> str:
    """Tiny GPT-NeoX/Pythia checkpoint in HF naming: fused
    head-interleaved query_key_value, parallel residual, partial rotary,
    untied embed_out head."""
    import numpy as np
    from safetensors.numpy import save_file

    out = Path(path)
    out.mkdir(parents=True, exist_ok=True)

    tokenizer = build_tokenizer(path)
    cfg = dict(TINY_GPT_NEOX_CONFIG)
    cfg["vocab_size"] = max(cfg["vocab_size"], len(tokenizer))
    with open(out / "config.json", "w") as f:
        json.dump(cfg, f, indent=2)

    rng = np.random.default_rng(seed)
    d = cfg["hidden_size"]
    inter = cfg["intermediate_size"]
    vocab = cfg["vocab_size"]

    def w(shape):
        return (rng.standard_normal(shape) * 0.02).astype(np.float32)

    def b(n):
        return (rng.standard_normal(n) * 0.01).astype(np.float32)

    tensors = {
        "gpt_neox.embed_in.weight": w((vocab, d)),
        "gpt_neox.final_layer_norm.weight": np.ones(d, np.float32),
        "gpt_neox.final_layer_norm.bias": b(d),
        "embed_out.weight": w((vocab, d)),
    }
    for i in range(cfg["num_hidden_layers"]):
        p = f"gpt_neox.layers.{i}"
        tensors |= {
            f"{p}.input_layernorm.weight": np.ones(d, np.float32),
            f"{p}.input_layernorm.bias": b(d),
            f"{p}.post_attention_layernorm.weight": np.ones(d, np.float32),
            f"{p}.post_attention_layernorm.bias": b(d),
            f"{p}.attention.query_key_value.weight": w((3 * d, d)),
            f"{p}.attention.query_key_value.bias": b(3 * d),
            f"{p}.attention.dense.weight": w((d, d)),
            f"{p}.attention.dense.bias": b(d),
            f"{p}.mlp.dense_h_to_4h.weight": w((inter, d)),
            f"{p}.mlp.dense_h_to_4h.bias": b(inter),
            f"{p}.mlp.dense_4h_to_h.weight": w((d, inter)),
            f"{p}.mlp.dense_4h_to_h.bias": b(d),
        }
    save_file(tensors, out / "model.safetensors")
    return str(out)


TINY_BLOOM_CONFIG = {
    "architectures": ["BloomForCausalLM"],
    "model_type": "bloom",
    "vocab_size": 512,
    "hidden_size": 64,
    "n_layer": 2,
    "n_head": 4,
    "layer_norm_epsilon": 1e-5,
    "apply_residual_connection_post_layernorm": False,
    "tie_word_embeddings": True,
    "bos_token_id": 1,
    "eos_token_id": 2,
    "torch_dtype": "float32",
}


def build_tiny_bloom(path: str, seed: int = 0) -> str:
    """Tiny BLOOM checkpoint in HF naming: ALiBi (no position params),
    word_embeddings_layernorm, fused head-interleaved query_key_value,
    tied head."""
    import numpy as np
    from safetensors.numpy import save_file

    out = Path(path)
    out.mkdir(parents=True, exist_ok=True)

    tokenizer = build_tokenizer(path)
    cfg = dict(TINY_BLOOM_CONFIG)
    cfg["vocab_size"] = max(cfg["vocab_size"], len(tokenizer))
    with open(out / "config.json", "w") as f:
        json.dump(cfg, f, indent=2)

    rng = np.random.default_rng(seed)
    d = cfg["hidden_size"]
    inter = 4 * d
    vocab = cfg["vocab_size"]

    def w(shape):
        return (rng.standard_normal(shape) * 0.02).astype(np.float32)

    def b(n):
        return (rng.standard_normal(n) * 0.01).astype(np.float32)

    tensors = {
        "word_embeddings.weight": w((vocab, d)),
        "word_embeddings_layernorm.weight": np.ones(d, np.float32),
        "word_embeddings_layernorm.bias": b(d),
        "ln_f.weight": np.ones(d, np.float32),
        "ln_f.bias": b(d),
    }
    for i in range(cfg["n_layer"]):
        p = f"h.{i}"
        tensors |= {
            f"{p}.input_layernorm.weight": np.ones(d, np.float32),
            f"{p}.input_layernorm.bias": b(d),
            f"{p}.post_attention_layernorm.weight": np.ones(d, np.float32),
            f"{p}.post_attention_layernorm.bias": b(d),
            f"{p}.self_attention.query_key_value.weight": w((3 * d, d)),
            f"{p}.self_attention.query_key_value.bias": b(3 * d),
            f"{p}.self_attention.dense.weight": w((d, d)),
            f"{p}.self_attention.dense.bias": b(d),
            f"{p}.mlp.dense_h_to_4h.weight": w((inter, d)),
            f"{p}.mlp.dense_h_to_4h.bias": b(inter),
            f"{p}.mlp.dense_4h_to_h.weight": w((d, inter)),
            f"{p}.mlp.dense_4h_to_h.bias": b(d),
        }
    save_file(tensors, out / "model.safetensors")
    return str(out)


TINY_GPT2_CONFIG = {
    "architectures": ["GPT2LMHeadModel"],
    "model_type": "gpt2",
    "vocab_size": 512,
    "n_embd": 64,
    "n_layer": 2,
    "n_head": 4,
    "n_positions": 512,
    "n_ctx": 512,
    "layer_norm_epsilon": 1e-5,
    "activation_function": "gelu_new",
    "bos_token_id": 1,
    "eos_token_id": 2,
    "torch_dtype": "float32",
}


def build_tiny_gpt2(path: str, seed: int = 0) -> str:
    """Tiny GPT-2 checkpoint in HF naming: Conv1D ([in, out]) weights,
    fused c_attn in plain q|k|v column thirds, wte/wpe, tied head."""
    import numpy as np
    from safetensors.numpy import save_file

    out = Path(path)
    out.mkdir(parents=True, exist_ok=True)

    tokenizer = build_tokenizer(path)
    cfg = dict(TINY_GPT2_CONFIG)
    cfg["vocab_size"] = max(cfg["vocab_size"], len(tokenizer))
    with open(out / "config.json", "w") as f:
        json.dump(cfg, f, indent=2)

    rng = np.random.default_rng(seed)
    d = cfg["n_embd"]
    inter = 4 * d
    vocab = cfg["vocab_size"]

    def w(shape):
        return (rng.standard_normal(shape) * 0.02).astype(np.float32)

    def b(n):
        return (rng.standard_normal(n) * 0.01).astype(np.float32)

    tensors = {
        "transformer.wte.weight": w((vocab, d)),
        "transformer.wpe.weight": w((cfg["n_positions"], d)),
        "transformer.ln_f.weight": np.ones(d, np.float32),
        "transformer.ln_f.bias": b(d),
    }
    for i in range(cfg["n_layer"]):
        p = f"transformer.h.{i}"
        tensors |= {
            f"{p}.ln_1.weight": np.ones(d, np.float32),
            f"{p}.ln_1.bias": b(d),
            f"{p}.ln_2.weight": np.ones(d, np.float32),
            f"{p}.ln_2.bias": b(d),
            f"{p}.attn.c_attn.weight": w((d, 3 * d)),  # Conv1D [in, out]
            f"{p}.attn.c_attn.bias": b(3 * d),
            f"{p}.attn.c_proj.weight": w((d, d)),
            f"{p}.attn.c_proj.bias": b(d),
            f"{p}.mlp.c_fc.weight": w((d, inter)),
            f"{p}.mlp.c_fc.bias": b(inter),
            f"{p}.mlp.c_proj.weight": w((inter, d)),
            f"{p}.mlp.c_proj.bias": b(d),
        }
    save_file(tensors, out / "model.safetensors")
    return str(out)


def build_tiny_lora_adapter(path: str, seed: int = 7, rank: int = 4,
                            arch: dict | None = None) -> str:
    """PEFT-format LoRA adapter matching the tiny llama fixture: real
    random A/B weights on q/v projections of both layers (the reference's
    fixture adapters carry dummy weights; ours are live so generation
    with the adapter measurably diverges from the base model).
    ``arch`` overrides the fixture config for non-tiny hosts (same keys
    as TINY_LLAMA_CONFIG)."""
    import json as json_mod

    import numpy as np
    from safetensors.numpy import save_file

    out = Path(path)
    out.mkdir(parents=True, exist_ok=True)
    cfg = arch or TINY_LLAMA_CONFIG
    d = cfg["hidden_size"]
    dh = cfg["head_dim"]
    h = cfg["num_attention_heads"]
    hkv = cfg["num_key_value_heads"]
    rng = np.random.default_rng(seed)

    json_mod.dump(
        {
            "peft_type": "LORA",
            "r": rank,
            "lora_alpha": 4 * rank,  # strong scaling: visible deltas
            "target_modules": ["q_proj", "v_proj"],
            "base_model_name_or_path": "tiny-llama",
        },
        open(out / "adapter_config.json", "w"),
        indent=2,
    )

    def w(shape):
        return (rng.standard_normal(shape) * 0.5).astype(np.float32)

    tensors = {}
    for i in range(cfg["num_hidden_layers"]):
        p = f"base_model.model.model.layers.{i}.self_attn"
        tensors[f"{p}.q_proj.lora_A.weight"] = w((rank, d))
        tensors[f"{p}.q_proj.lora_B.weight"] = w((h * dh, rank))
        tensors[f"{p}.v_proj.lora_A.weight"] = w((rank, d))
        tensors[f"{p}.v_proj.lora_B.weight"] = w((hkv * dh, rank))
    save_file(tensors, out / "adapter_model.safetensors")
    return str(out)


def build_tiny_gemma(path: str, seed: int = 0) -> str:
    """Tiny gemma-architecture checkpoint: llama-style tensor names with
    gemma block chemistry — GeGLU (gelu_pytorch_tanh), (1+w) RMSNorm,
    sqrt(hidden)-scaled embeddings, tied head (no lm_head tensor)."""
    import numpy as np
    from safetensors.numpy import save_file

    out = Path(path)
    out.mkdir(parents=True, exist_ok=True)

    tokenizer = build_tokenizer(path)
    cfg = dict(TINY_LLAMA_CONFIG)
    cfg["architectures"] = ["GemmaForCausalLM"]
    cfg["model_type"] = "gemma"
    cfg["hidden_activation"] = "gelu_pytorch_tanh"
    cfg["tie_word_embeddings"] = True
    cfg["vocab_size"] = max(cfg["vocab_size"], len(tokenizer))
    with open(out / "config.json", "w") as f:
        json.dump(cfg, f, indent=2)

    rng = np.random.default_rng(seed)
    d = cfg["hidden_size"]
    dh = cfg["head_dim"]
    h = cfg["num_attention_heads"]
    hkv = cfg["num_key_value_heads"]
    inter = cfg["intermediate_size"]
    vocab = cfg["vocab_size"]

    def w(shape):
        return (rng.standard_normal(shape) * 0.02).astype(np.float32)

    # HF gemma norms store w with (1+w) applied at runtime: random small
    # values (not ones) so the offset path is actually exercised
    def norm():
        return (rng.standard_normal(d) * 0.1).astype(np.float32)

    tensors = {
        "model.embed_tokens.weight": w((vocab, d)),
        "model.norm.weight": norm(),
    }
    for i in range(cfg["num_hidden_layers"]):
        p = f"model.layers.{i}"
        tensors |= {
            f"{p}.input_layernorm.weight": norm(),
            f"{p}.post_attention_layernorm.weight": norm(),
            f"{p}.self_attn.q_proj.weight": w((h * dh, d)),
            f"{p}.self_attn.k_proj.weight": w((hkv * dh, d)),
            f"{p}.self_attn.v_proj.weight": w((hkv * dh, d)),
            f"{p}.self_attn.o_proj.weight": w((d, h * dh)),
            f"{p}.mlp.gate_proj.weight": w((inter, d)),
            f"{p}.mlp.up_proj.weight": w((inter, d)),
            f"{p}.mlp.down_proj.weight": w((d, inter)),
        }
    save_file(tensors, out / "model.safetensors")
    return str(out)


def build_tiny_phi3(path: str, seed: int = 0) -> str:
    """Tiny phi3-architecture checkpoint: llama block chemistry with the
    HF phi-3 FUSED projections — qkv_proj (q|k|v stacked row slices) and
    gate_up_proj (gate over up) — untied head."""
    import numpy as np
    from safetensors.numpy import save_file

    out = Path(path)
    out.mkdir(parents=True, exist_ok=True)

    tokenizer = build_tokenizer(path)
    cfg = dict(TINY_LLAMA_CONFIG)
    cfg["architectures"] = ["Phi3ForCausalLM"]
    cfg["model_type"] = "phi3"
    cfg["pad_token_id"] = 0
    cfg["vocab_size"] = max(cfg["vocab_size"], len(tokenizer))
    with open(out / "config.json", "w") as f:
        json.dump(cfg, f, indent=2)

    rng = np.random.default_rng(seed)
    d = cfg["hidden_size"]
    dh = cfg["head_dim"]
    h = cfg["num_attention_heads"]
    hkv = cfg["num_key_value_heads"]
    inter = cfg["intermediate_size"]
    vocab = cfg["vocab_size"]

    def w(shape):
        return (rng.standard_normal(shape) * 0.02).astype(np.float32)

    tensors = {
        "model.embed_tokens.weight": w((vocab, d)),
        "model.norm.weight": np.ones(d, dtype=np.float32),
        "lm_head.weight": w((vocab, d)),
    }
    for i in range(cfg["num_hidden_layers"]):
        p = f"model.layers.{i}"
        tensors |= {
            f"{p}.input_layernorm.weight": np.ones(d, dtype=np.float32),
            f"{p}.post_attention_layernorm.weight": np.ones(
                d, dtype=np.float32
            ),
            f"{p}.self_attn.qkv_proj.weight": w(((h + 2 * hkv) * dh, d)),
            f"{p}.self_attn.o_proj.weight": w((d, h * dh)),
            f"{p}.mlp.gate_up_proj.weight": w((2 * inter, d)),
            f"{p}.mlp.down_proj.weight": w((d, inter)),
        }
    save_file(tensors, out / "model.safetensors")
    return str(out)


def build_tiny_qwen3(path: str, seed: int = 0) -> str:
    """Tiny qwen3-architecture checkpoint: llama tensor names plus
    per-layer head-dim q_norm/k_norm weights."""
    import numpy as np
    from safetensors.numpy import save_file

    out = Path(path)
    out.mkdir(parents=True, exist_ok=True)

    tokenizer = build_tokenizer(path)
    cfg = dict(TINY_LLAMA_CONFIG)
    cfg["architectures"] = ["Qwen3ForCausalLM"]
    cfg["model_type"] = "qwen3"
    cfg["vocab_size"] = max(cfg["vocab_size"], len(tokenizer))
    with open(out / "config.json", "w") as f:
        json.dump(cfg, f, indent=2)

    rng = np.random.default_rng(seed)
    d = cfg["hidden_size"]
    dh = cfg["head_dim"]
    h = cfg["num_attention_heads"]
    hkv = cfg["num_key_value_heads"]
    inter = cfg["intermediate_size"]
    vocab = cfg["vocab_size"]

    def w(shape):
        return (rng.standard_normal(shape) * 0.02).astype(np.float32)

    def norm(n):
        return (1.0 + rng.standard_normal(n) * 0.1).astype(np.float32)

    tensors = {
        "model.embed_tokens.weight": w((vocab, d)),
        "model.norm.weight": norm(d),
        "lm_head.weight": w((vocab, d)),
    }
    for i in range(cfg["num_hidden_layers"]):
        p = f"model.layers.{i}"
        tensors |= {
            f"{p}.input_layernorm.weight": norm(d),
            f"{p}.post_attention_layernorm.weight": norm(d),
            f"{p}.self_attn.q_proj.weight": w((h * dh, d)),
            f"{p}.self_attn.k_proj.weight": w((hkv * dh, d)),
            f"{p}.self_attn.v_proj.weight": w((hkv * dh, d)),
            f"{p}.self_attn.o_proj.weight": w((d, h * dh)),
            f"{p}.self_attn.q_norm.weight": norm(dh),
            f"{p}.self_attn.k_norm.weight": norm(dh),
            f"{p}.mlp.gate_proj.weight": w((inter, d)),
            f"{p}.mlp.up_proj.weight": w((inter, d)),
            f"{p}.mlp.down_proj.weight": w((d, inter)),
        }
    save_file(tensors, out / "model.safetensors")
    return str(out)


# ---------------------------------------------------------- int4 checkpoints


def _pack_int32_nibbles(vals, axis):
    """int4 values → int32 words, 8 per word along ``axis`` (sequential
    nibble order) — the inverse of engine/quantized._unpack_int32_nibbles."""
    import numpy as np

    vals = np.asarray(vals).astype(np.int64) & 0xF
    vals = vals.astype(np.uint32)
    new_shape = list(vals.shape)
    new_shape[axis] //= 8
    grouped = vals.reshape(
        new_shape[:axis] + [new_shape[axis], 8] + new_shape[axis + 1:]
    )
    shifts = (np.arange(8, dtype=np.uint32) * 4).reshape(
        (1,) * (axis + 1) + (8,) + (1,) * (grouped.ndim - axis - 2)
    )
    # ascontiguousarray: safetensors serialises the raw buffer, so a
    # non-contiguous result would be written scrambled
    return np.ascontiguousarray(
        (grouped << shifts).sum(axis=axis + 1).astype(np.int32)
    )


def quantize_checkpoint_int4(src_dir, dst_dir, *, method="awq",
                             group_size=8, desc_act=False, seed=0):
    """Re-write a tiny fp checkpoint in the AWQ / AutoGPTQ int4 wire
    format (qweight/qzeros/scales[/g_idx] + quantization_config) so the
    dequant-on-load path (engine/quantized.py) can be pinned without
    network access.  Returns the destination path."""
    import json
    import shutil
    from pathlib import Path

    import numpy as np
    from safetensors.numpy import save_file

    from safetensors import safe_open

    AWQ_ORDER = (0, 2, 4, 6, 1, 3, 5, 7)
    src, dst = Path(src_dir), Path(dst_dir)
    dst.mkdir(parents=True, exist_ok=True)
    for f in src.iterdir():
        if f.name != "model.safetensors":
            shutil.copy(f, dst / f.name)

    rng = np.random.default_rng(seed)
    quant_suffixes = ("q_proj.weight", "k_proj.weight", "v_proj.weight",
                      "o_proj.weight", "gate_proj.weight",
                      "up_proj.weight", "down_proj.weight",
                      # phi-3 fused projections quantize as single linears
                      "qkv_proj.weight", "gate_up_proj.weight")
    out_tensors = {}
    with safe_open(src / "model.safetensors", framework="numpy") as fh:
        for name in fh.keys():
            w = fh.get_tensor(name)
            if not name.endswith(quant_suffixes):
                out_tensors[name] = w
                continue
            prefix = name[: -len(".weight")]
            wt = w.astype(np.float32).T  # [in, out]
            in_f, out_f = wt.shape
            assert in_f % group_size == 0 and out_f % 8 == 0
            groups = in_f // group_size
            if method == "gptq" and desc_act:
                g_idx = rng.permutation(
                    np.repeat(np.arange(groups), group_size)
                ).astype(np.int32)
            else:
                g_idx = np.repeat(np.arange(groups), group_size)
            # per (group, out-col) asymmetric int4 quantization
            scales = np.zeros((groups, out_f), np.float32)
            zeros = np.zeros((groups, out_f), np.int32)
            q = np.zeros((in_f, out_f), np.int32)
            for g in range(groups):
                rows = np.nonzero(g_idx == g)[0]
                block = wt[rows]
                # the quantization range must include 0 so the zero-point
                # lands in [0, 15] (an all-negative group would otherwise
                # clip z and shift the whole block by |hi|)
                lo = np.minimum(block.min(axis=0), 0.0)
                hi = np.maximum(block.max(axis=0), 0.0)
                s = np.maximum((hi - lo) / 15.0, 1e-8)
                # gptq: floor 1 keeps the stored-minus-one convention
                # invertible (z=0 would wrap to 15 on unpack)
                z_floor = 1 if method == "gptq" else 0
                z = np.clip(np.round(-lo / s), z_floor, 15)
                scales[g], zeros[g] = s, z.astype(np.int32)
                q[rows] = np.clip(
                    np.round(block / s) + z, 0, 15
                ).astype(np.int32)
            if method == "awq":
                # nibble interleave along out: inverse of the unpack order
                order = np.arange(out_f).reshape(-1, 8)[
                    :, list(AWQ_ORDER)
                ].reshape(-1)
                inv = np.empty_like(order)
                inv[order] = np.arange(out_f)
                out_tensors[f"{prefix}.qweight"] = _pack_int32_nibbles(
                    q[:, inv], axis=1)
                out_tensors[f"{prefix}.qzeros"] = _pack_int32_nibbles(
                    zeros[:, inv], axis=1)
                out_tensors[f"{prefix}.scales"] = scales.astype(np.float16)
            else:  # gptq
                out_tensors[f"{prefix}.qweight"] = _pack_int32_nibbles(
                    q, axis=0)
                # classic stored-minus-one zero-point convention
                out_tensors[f"{prefix}.qzeros"] = _pack_int32_nibbles(
                    zeros - 1, axis=1)
                out_tensors[f"{prefix}.scales"] = scales.astype(np.float16)
                if desc_act:
                    out_tensors[f"{prefix}.g_idx"] = g_idx
    save_file(out_tensors, dst / "model.safetensors")

    cfg_path = dst / "config.json"
    cfg = json.loads(cfg_path.read_text())
    if method == "awq":
        cfg["quantization_config"] = {
            "quant_method": "awq", "bits": 4, "group_size": group_size,
            "version": "gemm", "zero_point": True,
        }
    else:
        cfg["quantization_config"] = {
            "quant_method": "gptq", "bits": 4, "group_size": group_size,
            "desc_act": desc_act, "sym": False,
        }
    cfg_path.write_text(json.dumps(cfg, indent=2))
    return str(dst)
