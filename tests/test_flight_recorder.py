"""Flight recorder + stall watchdog + live introspection (docs/OBSERVABILITY.md).

CPU-backed: the ring buffer's bounds/eviction/ordering contracts, the
snapshot serializers' golden shape, watchdog stall detection (heartbeat
starvation fires a dump; an in-flight compile suspends it; dump file +
termination log carry the snapshot), and the HTTP debug surfaces via the
real app dispatch.  The gRPC twins of these endpoints are covered in
test_grpc_server.py (they need generated pb modules).
"""

from __future__ import annotations

import asyncio
import json
import re

import pytest


def _sample(text: str, name: str, labels: tuple[str, ...] = ()) -> float:
    for line in text.splitlines():
        m = re.match(rf"^{re.escape(name)}(\{{[^}}]*\}})? (\S+)$", line)
        if m and all(lbl in (m.group(1) or "") for lbl in labels):
            return float(m.group(2))
    return 0.0


def _scrape() -> str:
    from vllm_tgis_adapter_tpu import metrics

    return metrics.render().decode()


# ------------------------------------------------------------- ring buffer


def test_ring_bounds_and_eviction():
    from vllm_tgis_adapter_tpu.flight_recorder import FlightRecorder

    rec = FlightRecorder(capacity=8)
    for i in range(20):
        rec.record("admit", f"r{i}", step=i)
    assert len(rec) == 8
    assert rec.total_recorded == 20
    events = rec.events()
    # oldest 12 evicted; survivors keep arrival order
    assert [e["request_id"] for e in events] == [
        f"r{i}" for i in range(12, 20)
    ]
    assert [e["request_id"] for e in rec.events(last_n=3)] == [
        "r17", "r18", "r19"
    ]
    # evicted requests leave no timeline
    assert rec.events_for("r0") == []
    assert len(rec.events_for("r19")) == 1


def test_event_ordering_fields_and_metrics():
    from vllm_tgis_adapter_tpu.flight_recorder import FlightRecorder

    before = _sample(
        _scrape(), "tgis_tpu_flight_recorder_events_total",
        ('kind="preempt"',),
    )
    rec = FlightRecorder()
    rec.record("admit", "req-1", step=1, prompt_tokens=7)
    rec.record("decode", step=2, num_seqs=3, batch_bucket=4)
    rec.record("preempt", "req-1", step=3, was_running=True)
    events = rec.events()
    assert [e["kind"] for e in events] == ["admit", "decode", "preempt"]
    # monotonic stamps are non-decreasing: the ring IS the ordering
    monos = [e["mono_ns"] for e in events]
    assert monos == sorted(monos)
    assert events[0]["detail"] == {"prompt_tokens": 7}
    assert events[0]["step"] == 1
    assert "request_id" not in events[1]  # batch-level event
    assert events[2]["detail"] == {"was_running": True}
    after = _sample(
        _scrape(), "tgis_tpu_flight_recorder_events_total",
        ('kind="preempt"',),
    )
    assert after - before == 1


def test_trace_id_correlation():
    from vllm_tgis_adapter_tpu.flight_recorder import FlightRecorder

    rec = FlightRecorder()
    rec.record("admit", "req-a", trace_id="a" * 32)
    rec.record("admit", "req-b", trace_id="b" * 32)
    rec.record("finish", "req-a", trace_id="a" * 32, reason="stop")
    timeline = rec.events_for("req-a")
    assert [e["kind"] for e in timeline] == ["admit", "finish"]
    assert all(e["trace_id"] == "a" * 32 for e in timeline)


# ------------------------------------------------------------ serializers


def test_allocator_stats_golden_shape():
    from vllm_tgis_adapter_tpu.engine.kv_cache import BlockAllocator
    from vllm_tgis_adapter_tpu.flight_recorder import allocator_stats

    alloc = BlockAllocator(16, 4)
    held = alloc.allocate(4)
    stats = allocator_stats(alloc)
    assert stats == {
        "num_blocks": 16,
        "used": 4,
        "free": 12,
        "cached_free": 0,
        "occupancy": 4 / 16,
        "fragmentation": 0.0,
        "free_epochs_open": 0,
        "quarantined": 0,
        "prefix_hit_tokens": 0,
    }
    # frees inside an open epoch quarantine instead of freeing
    alloc.begin_free_epoch()
    alloc.free(held)
    stats = allocator_stats(alloc)
    assert stats["free_epochs_open"] == 1
    assert stats["quarantined"] == 4
    assert stats["used"] == 4  # still held until the epoch flushes
    alloc.flush_free_epoch()
    assert allocator_stats(alloc)["used"] == 0


def test_scheduler_queue_snapshot():
    from vllm_tgis_adapter_tpu.engine.config import (
        CacheConfig,
        SchedulerConfig,
    )
    from vllm_tgis_adapter_tpu.engine.sampling_params import SamplingParams
    from vllm_tgis_adapter_tpu.engine.scheduler import Scheduler
    from vllm_tgis_adapter_tpu.engine.sequence import Sequence
    from vllm_tgis_adapter_tpu.flight_recorder import scheduler_queues

    sched = Scheduler(
        SchedulerConfig(max_num_seqs=4, prefill_buckets=(32,)),
        CacheConfig(block_size=16, num_blocks=8, cache_dtype="float32"),
        num_blocks=8,
    )
    seq = Sequence("snap-1", None, [1, 2, 3], SamplingParams(max_tokens=4))
    seq.trace_id = "c" * 32
    sched.add(seq)
    snap = scheduler_queues(sched)
    assert snap["num_unfinished"] == 1
    assert snap["running"] == [] and snap["swapped"] == []
    (info,) = snap["waiting"]
    assert info["request_id"] == "snap-1"
    assert info["status"] == "WAITING"
    assert info["prompt_tokens"] == 3
    assert info["trace_id"] == "c" * 32
    assert info["age_s"] >= 0
    json.dumps(snap)  # the snapshot must be JSON-serializable as-is


# ------------------------------------------------------------ real engine


def _build_engine(tiny_model_dir, **overrides):
    from vllm_tgis_adapter_tpu.engine.async_llm import AsyncLLMEngine
    from vllm_tgis_adapter_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        LoRAConfig,
        ModelConfig,
        ParallelConfig,
        SchedulerConfig,
    )

    mcfg = ModelConfig.from_pretrained(tiny_model_dir, dtype="float32")
    config = EngineConfig(
        model_config=mcfg,
        cache_config=CacheConfig(
            block_size=16, num_blocks=64, cache_dtype=mcfg.dtype
        ),
        scheduler_config=SchedulerConfig(
            max_num_seqs=2, prefill_buckets=(32, 64)
        ),
        parallel_config=ParallelConfig(),
        lora_config=LoRAConfig(),
        **overrides,
    )
    return AsyncLLMEngine.from_config(config)


async def _generate_one(engine, request_id: str, max_tokens: int = 4):
    from vllm_tgis_adapter_tpu.engine.sampling_params import SamplingParams

    final = None
    async for out in engine.generate(
        prompt=None,
        sampling_params=SamplingParams(
            temperature=0.0, max_tokens=max_tokens, ignore_eos=True
        ),
        request_id=request_id,
        prompt_token_ids=list(range(3, 20)),
    ):
        final = out
    return final


def test_engine_records_lifecycle_and_debug_state(tiny_model_dir):
    """A served request leaves an admit → prefill → finish timeline in
    the recorder, and debug_state() carries queues, KV stats, compile
    state, and those events in one JSON-serializable snapshot."""
    engine = _build_engine(tiny_model_dir)

    async def scenario():
        await _generate_one(engine, "fr-live-1")
        state = engine.debug_state()
        trace = engine.request_trace("fr-live-1")
        missing = engine.request_trace("never-admitted")
        await engine.stop()
        return state, trace, missing

    state, trace, missing = asyncio.run(scenario())
    json.dumps(state)  # wire-ready as-is

    assert state["engine"]["replicas"] == 1
    (replica,) = state["replicas"]
    assert replica["scheduler"]["num_unfinished"] == 0
    assert replica["kv_cache"]["num_blocks"] == 64
    assert 0.0 <= replica["kv_cache"]["occupancy"] <= 1.0
    assert state["compile_tracker"]["compiled_shapes"] >= 0
    assert state["watchdog"]["deadline_s"] == 120.0
    kinds = {e["kind"] for e in state["events"]}
    assert {"admit", "ragged_step", "finish"} <= kinds

    assert missing is None
    assert trace["request_id"] == "fr-live-1"
    assert trace["live"] is None  # finished: no longer resident
    t_kinds = [e["kind"] for e in trace["events"]]
    # the cost ledger closes right after the terminal outcome, so the
    # trace ends finish -> ledger
    assert t_kinds[0] == "admit" and t_kinds[-2:] == ["finish", "ledger"]
    # finish carries the reason; every event of one request shares a step
    # ordering consistent with the engine's dispatch counter
    assert trace["events"][-2]["detail"]["reason"] == "length"
    assert trace["events"][-1]["detail"]["outcome"] == "finish"
    steps = [e["step"] for e in trace["events"]]
    assert steps == sorted(steps)


def test_abort_event_recorded(tiny_model_dir):
    engine = _build_engine(tiny_model_dir)

    async def scenario():
        from vllm_tgis_adapter_tpu.engine.sampling_params import (
            SamplingParams,
        )

        gen = engine.generate(
            prompt=None,
            sampling_params=SamplingParams(
                temperature=0.0, max_tokens=500, ignore_eos=True
            ),
            request_id="fr-abort-1",
            prompt_token_ids=list(range(3, 20)),
        )
        await gen.__anext__()  # wait until it is producing
        await engine.abort("fr-abort-1")
        await gen.aclose()
        for _ in range(100):
            if not engine.engine.has_unfinished_requests():
                break
            await asyncio.sleep(0.02)
        trace = engine.request_trace("fr-abort-1")
        await engine.stop()
        return trace

    trace = asyncio.run(scenario())
    assert "abort" in [e["kind"] for e in trace["events"]]


# --------------------------------------------------------------- watchdog


@pytest.fixture()
def _clean_tracker():
    from vllm_tgis_adapter_tpu import compile_tracker

    compile_tracker.reset()
    yield
    compile_tracker.reset()


def _watchdog(tmp_path, term_log, **kwargs):
    from vllm_tgis_adapter_tpu.watchdog import StallWatchdog

    defaults = dict(
        snapshot_fn=lambda: {"replicas": [], "events": []},
        active_fn=lambda: True,
        deadline_s=0.05,
        dump_dir=str(tmp_path / "dumps"),
        termination_log=str(term_log),
    )
    defaults.update(kwargs)
    return StallWatchdog(**defaults)


def test_watchdog_fires_on_heartbeat_starvation(tmp_path, _clean_tracker):
    term_log = tmp_path / "termination-log"
    term_log.write_text("")  # must exist (write_termination_log contract)
    stalls_0 = _sample(_scrape(), "tgis_tpu_watchdog_stalls_total")

    async def scenario():
        wd = _watchdog(tmp_path, term_log)
        wd.beat()
        assert await wd.check() is None  # fresh heartbeat: healthy
        await asyncio.sleep(0.08)
        fired = await wd.check()
        again = await wd.check()  # same episode: one dump only
        wd.beat()
        assert await wd.check() is None  # recovered: re-armed
        return wd, fired, again

    wd, fired, again = asyncio.run(scenario())
    assert fired is not None and again is None
    assert fired["reason"] == "step-loop heartbeat stall"
    assert fired["heartbeat_age_s"] > 0.05

    # dump file landed under --dump-dir with the full snapshot
    assert wd.last_dump_path is not None
    on_disk = json.loads(open(wd.last_dump_path).read())
    assert on_disk["reason"] == "step-loop heartbeat stall"
    assert "replicas" in on_disk and "events" in on_disk

    # termination log names the stall and points at the dump
    term = term_log.read_text()
    assert "stalled" in term and wd.last_dump_path in term

    after = _sample(_scrape(), "tgis_tpu_watchdog_stalls_total")
    assert after - stalls_0 == 1
    assert _sample(
        _scrape(), "tgis_tpu_watchdog_last_heartbeat_age_seconds"
    ) >= 0


def test_watchdog_idle_engine_never_fires(tmp_path, _clean_tracker):
    term_log = tmp_path / "t"
    term_log.write_text("")

    async def scenario():
        wd = _watchdog(tmp_path, term_log, active_fn=lambda: False)
        await asyncio.sleep(0.08)
        return await wd.check()

    assert asyncio.run(scenario()) is None


def test_watchdog_suspended_while_compile_in_flight(
    tmp_path, _clean_tracker
):
    from vllm_tgis_adapter_tpu import compile_tracker

    term_log = tmp_path / "t"
    term_log.write_text("")

    async def scenario():
        wd = _watchdog(tmp_path, term_log)
        await asyncio.sleep(0.08)
        token = compile_tracker.begin_dispatch("decode")
        suspended = await wd.check()  # compile in flight: no stall
        compile_tracker.end_dispatch(token)
        fired = await wd.check()  # compile retired, still no beat: stall
        return suspended, fired

    suspended, fired = asyncio.run(scenario())
    assert suspended is None
    assert fired is not None


def test_watchdog_compile_grace_is_bounded(tmp_path, _clean_tracker):
    """A 'compile' that outlives the grace window is a hang: fire."""
    from vllm_tgis_adapter_tpu import compile_tracker

    term_log = tmp_path / "t"
    term_log.write_text("")

    async def scenario():
        wd = _watchdog(tmp_path, term_log, compile_grace_s=0.0)
        await asyncio.sleep(0.08)
        token = compile_tracker.begin_dispatch("decode")
        try:
            return await wd.check()
        finally:
            compile_tracker.end_dispatch(token)

    assert asyncio.run(scenario()) is not None


def test_simulated_stall_dumps_real_engine_state(tiny_model_dir, tmp_path):
    """Acceptance: a simulated step-loop stall on a REAL engine produces
    a JSON snapshot containing the scheduler queues (with the stuck
    request), KV occupancy, and the flight recorder's recent events."""
    import time as _time

    engine = _build_engine(
        tiny_model_dir,
        watchdog_deadline_s=0.05,
        dump_dir=str(tmp_path / "dumps"),
    )
    term_log = tmp_path / "termination-log"
    term_log.write_text("")
    engine.watchdog._termination_log = str(term_log)
    engine.watchdog.check_interval_s = 0.01

    async def scenario():
        from vllm_tgis_adapter_tpu.engine.sampling_params import (
            SamplingParams,
        )

        # admit a request directly into the core engine WITHOUT starting
        # the step loops — work exists, nothing beats: a stall
        rep = engine._replicas[0]
        async with rep.lock:
            rep.engine.add_request(
                "stuck-1", None,
                SamplingParams(temperature=0.0, max_tokens=4),
                prompt_token_ids=list(range(3, 20)),
            )
        rep.last_beat = _time.monotonic() - 60.0
        fired = await engine.watchdog.check()
        # the watchdog's own task loop is exercised separately above;
        # here the tick is driven directly for determinism
        await engine.stop()
        return fired

    fired = asyncio.run(scenario())
    assert fired is not None
    dump = json.loads(open(engine.watchdog.last_dump_path).read())
    waiting = dump["replicas"][0]["scheduler"]["waiting"]
    assert [w["request_id"] for w in waiting] == ["stuck-1"]
    assert dump["replicas"][0]["heartbeat_age_s"] > 50
    assert "occupancy" in dump["replicas"][0]["kv_cache"]
    kinds = [e["kind"] for e in dump["events"]]
    assert "admit" in kinds and kinds[-1] == "stall"
    assert term_log.read_text().strip()


# --------------------------------------------------------- HTTP endpoints


def _debug_app(engine, tiny_model_dir):
    import argparse

    from vllm_tgis_adapter_tpu.http import build_http_server

    args = argparse.Namespace(
        served_model_name=None, model=tiny_model_dir, api_key=None,
        root_path=None, profile_dir=None,
    )
    return build_http_server(args, engine)


def test_http_debug_state_and_request_trace(tiny_model_dir):
    from vllm_tgis_adapter_tpu.http import HttpRequest

    engine = _build_engine(tiny_model_dir)
    app = _debug_app(engine, tiny_model_dir)

    async def scenario():
        await _generate_one(engine, "http-debug-1")
        state_resp = await app.dispatch(
            HttpRequest("GET", "/debug/state", {}, b"")
        )
        trace_resp = await app.dispatch(
            HttpRequest("GET", "/debug/requests/http-debug-1", {}, b"")
        )
        missing_resp = await app.dispatch(
            HttpRequest("GET", "/debug/requests/no-such-request", {}, b"")
        )
        method_resp = await app.dispatch(
            HttpRequest("POST", "/debug/state", {}, b"")
        )
        await engine.stop()
        return state_resp, trace_resp, missing_resp, method_resp

    state_resp, trace_resp, missing_resp, method_resp = asyncio.run(
        scenario()
    )
    assert state_resp.status == 200
    state = json.loads(state_resp.body)
    assert state["replicas"][0]["kv_cache"]["num_blocks"] == 64
    assert any(e["kind"] == "finish" for e in state["events"])

    assert trace_resp.status == 200
    trace = json.loads(trace_resp.body)
    assert trace["request_id"] == "http-debug-1"
    assert trace["events"][0]["kind"] == "admit"

    assert missing_resp.status == 404
    assert method_resp.status == 405


def test_http_metrics_expose_watchdog_and_recorder_families(
    tiny_model_dir,
):
    from vllm_tgis_adapter_tpu.http import HttpRequest

    engine = _build_engine(tiny_model_dir)
    app = _debug_app(engine, tiny_model_dir)

    async def scenario() -> bytes:
        response = await app.dispatch(HttpRequest("GET", "/metrics", {}, b""))
        await engine.stop()
        return response.body

    body = asyncio.run(scenario()).decode()
    for family in (
        "tgis_tpu_flight_recorder_events_total",
        "tgis_tpu_watchdog_last_heartbeat_age_seconds",
        "tgis_tpu_watchdog_stalls_total",
    ):
        assert family in body, f"{family} missing from /metrics"
