"""tpulint analyzer tests: every rule code in both directions.

``FIXTURES`` maps each rule code to a (firing, clean) pair of snippet
modules; one parametrized test asserts the firing snippet raises exactly
that code and the clean snippet raises nothing.  Separate tests cover the
suppression contract (reasoned disables suppress, reason-less disables
are TPL000) and the self-check: the shipped package must be
tpulint-clean (exit 0) with zero unexplained suppressions.
"""

from __future__ import annotations

import sys
import textwrap
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from tools.tpulint import config as lint_config  # noqa: E402
from tools.tpulint.analyzer import analyze_file  # noqa: E402
from tools.tpulint.cli import main as tpulint_main  # noqa: E402

STEP_PATH = "pkg/engine/runner.py"  # classified as step-loop
ASYNC_PATH = "pkg/grpc/server.py"  # any module; rules key off async def


#: pinned manifest for the fixtures: the TPL601 clean/firing snippets
#: resolve against THIS dict, never the live checked-in manifest — an
#: intentional lattice change must not break unrelated rule-unit tests.
FIXTURE_MANIFEST = {
    ("engine/runner.py", "prefill"): {
        "module": "engine/runner.py", "name": "prefill",
        "static_argnums": [], "static_argnames": [],
        "partial_kwargs": [], "partial_pos": 0, "donate": True,
    },
}


def lint(tmp_path: Path, rel: str, source: str):
    """Write ``source`` at ``rel`` under tmp_path and analyze it."""
    target = tmp_path / rel
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text(textwrap.dedent(source))
    return analyze_file(target, root=tmp_path, manifest=FIXTURE_MANIFEST)


def active_codes(findings) -> list[str]:
    return [f.code for f in findings if not f.suppressed]


# --------------------------------------------------------------- fixtures

FIXTURES: dict[str, tuple[str, str, str]] = {
    # code: (path, firing snippet, clean snippet)
    "TPL000": (
        STEP_PATH,
        """
        import numpy as np
        def pull(packed_dev):
            return np.asarray(packed_dev)  # tpulint: disable=TPL202
        """,
        """
        import numpy as np
        def pull(packed_dev):
            return np.asarray(packed_dev)  # tpulint: disable=TPL202(one sanctioned fetch)
        """,
    ),
    "TPL101": (
        STEP_PATH,
        """
        import jax
        @jax.jit
        def f(x, n):
            if x.shape[0] > n:
                return x
            return x * 2
        """,
        """
        import jax
        from functools import partial
        @partial(jax.jit, static_argnames=("n",))
        def f(x, n=4):
            if n > 2:
                return x
            if x is None:
                return x
            return x * 2
        """,
    ),
    "TPL102": (
        STEP_PATH,
        """
        import jax
        @jax.jit
        def f(x, table):
            return table[f"bucket-{x.shape[0]}"]
        """,
        """
        import jax
        @jax.jit
        def f(x, table):
            if x is None:
                raise ValueError(f"bad shape {x.shape}")
            return table["bucket"]
        """,
    ),
    "TPL103": (
        STEP_PATH,
        """
        import jax
        def g(x, num_steps: int, flashy: bool = True):
            return x
        fn = jax.jit(g)
        """,
        """
        import jax
        def g(x, num_steps: int, flashy: bool = True):
            return x
        fn = jax.jit(g, static_argnums=(1,), static_argnames=("flashy",))
        """,
    ),
    "TPL104": (
        STEP_PATH,
        """
        import jax
        def build(model):
            return jax.jit(model.decode)
        """,
        """
        import jax
        def build(model, sh):
            a = jax.jit(model.decode, donate_argnums=(1,))
            b = jax.jit(lambda: model.make_kv_caches(8), out_shardings=sh)
            c = jax.jit(model.propose)
            return a, b, c
        """,
    ),
    "TPL201": (
        STEP_PATH,
        """
        import jax
        def step(x):
            x.block_until_ready()
            return x[0].item() + jax.device_get(x)[1]
        """,
        """
        import jax
        def step(x):
            return x[0] + x[1]
        """,
    ),
    "TPL202": (
        STEP_PATH,
        """
        import numpy as np
        def pull(packed_dev, logits):
            return np.asarray(packed_dev), float(logits[0])
        """,
        """
        import numpy as np
        def host_prep(rows, slots):
            return np.asarray(rows), np.asarray([1, 2]), int(slots[0])
        """,
    ),
    "TPL301": (
        ASYNC_PATH,
        """
        import time
        async def handler():
            time.sleep(0.1)
        """,
        """
        import asyncio, time
        async def handler():
            await asyncio.sleep(0.1)
        def sync_helper():
            time.sleep(0.1)
        """,
    ),
    "TPL302": (
        ASYNC_PATH,
        """
        from pathlib import Path
        async def handler(path):
            with open(path) as f:
                pass
            return Path(path).read_text()
        """,
        """
        import asyncio
        from pathlib import Path
        def _read(path):
            with open(path) as f:
                return f.read()
        async def handler(path):
            def inner():
                return Path(path).read_text()
            return await asyncio.to_thread(_read, path)
        """,
    ),
    "TPL303": (
        ASYNC_PATH,
        """
        async def loop(engine, plan):
            return engine.wait_step(plan)
        """,
        """
        import asyncio
        async def loop(engine, plan):
            await engine.precompile("all")
            return await asyncio.to_thread(engine.wait_step, plan)
        """,
    ),
    # --- TPL4xx lock discipline -----------------------------------------
    "TPL401": (
        "pkg/engine/kv_tier.py",
        """
        import asyncio
        class Tier:
            async def demote(self, other):
                async with self._transfer_lock:
                    await other.fetch()
        """,
        """
        import asyncio
        class Tier:
            async def demote(self, batch):
                async with self._transfer_lock:
                    host = await asyncio.to_thread(self._to_host, batch)
                self._insert(host)
        """,
    ),
    "TPL402": (
        "pkg/engine/core.py",
        """
        import threading
        a_lock = threading.Lock()
        b_lock = threading.Lock()
        def one():
            with a_lock:
                with b_lock:
                    pass
        def two():
            with b_lock:
                with a_lock:
                    pass
        """,
        """
        import threading
        a_lock = threading.Lock()
        b_lock = threading.Lock()
        def one():
            with a_lock:
                with b_lock:
                    pass
        def two():
            with a_lock:
                with b_lock:
                    pass
        """,
    ),
    "TPL403": (
        "pkg/engine/adapter_pool.py",
        """
        import asyncio
        class Pool:
            async def stream(self):
                self.swaps = 1
                await asyncio.to_thread(self.worker)
            def worker(self):
                self.swaps = 2
        """,
        """
        import asyncio, threading
        class Pool:
            async def stream(self):
                with self._lock:
                    self.swaps = 1
                await asyncio.to_thread(self.worker)
            def worker(self):
                with self._lock:
                    self.swaps = 2
        """,
    ),
    # --- TPL304 bpo-42130 wait_for(event.wait()) ------------------------
    "TPL304": (
        ASYNC_PATH,
        """
        import asyncio
        async def pump(self):
            await asyncio.wait_for(self._wake.wait(), 1.0)
        """,
        """
        import asyncio
        async def pump(self):
            waiter = asyncio.get_running_loop().create_future()
            self._waiters.append(waiter)
            await asyncio.wait_for(waiter, 1.0)
        """,
    ),
    # --- TPL5xx resource pairing ----------------------------------------
    "TPL501": (
        "pkg/engine/core.py",
        """
        def admit(self, seq):
            self.lora_manager.pin(seq.lora_name)
            self.scheduler.add(seq)
            self.lora_manager.unpin(seq.lora_name)
        """,
        """
        def admit(self, seq):
            self.lora_manager.pin(seq.lora_name)
            try:
                self.scheduler.add(seq)
            finally:
                self.lora_manager.unpin(seq.lora_name)
        """,
    ),
    "TPL502": (
        "pkg/engine/kv_tier.py",
        """
        import asyncio
        class Tier:
            def submit(self, batch):
                asyncio.create_task(self._demote(batch))
        """,
        """
        from vllm_tgis_adapter_tpu.utils import spawn_task
        class Tier:
            def submit(self, batch):
                spawn_task(
                    self._demote(batch), name="demote",
                    retain=self._tasks,
                )
        """,
    ),
    # --- TPL51x lifecycle grammar ---------------------------------------
    "TPL511": (
        "pkg/engine/core.py",
        """
        def note(self, rid):
            self.recorder.record("warp_speed", rid)
        """,
        """
        def note(self, rid):
            self.recorder.record("admit", rid)
            self.recorder.record("decode", num_seqs=4)
        """,
    ),
    "TPL512": (
        "pkg/supervisor/supervisor.py",
        """
        from vllm_tgis_adapter_tpu.engine import sanitizer
        def resurrect(self):
            sanitizer.check_lifecycle_edge("dead", "serving")
            self.engine.lifecycle = "serving"
        """,
        """
        from vllm_tgis_adapter_tpu.engine import sanitizer
        def drain(self):
            sanitizer.check_lifecycle_edge("serving", "draining")
            self.engine.lifecycle = "draining"
        """,
    ),
    # --- TPL6xx compile-lattice manifest (per-file half) ----------------
    "TPL601": (
        "pkg/engine/runner.py",
        """
        import jax
        from vllm_tgis_adapter_tpu.compile_tracker import track_jit
        def build(model):
            return track_jit("bogus_step", jax.jit(model.decode_bogus))
        """,
        """
        import jax
        from vllm_tgis_adapter_tpu.compile_tracker import track_jit
        def build(model, donate):
            return track_jit(
                "prefill",
                jax.jit(model.prefill, donate_argnums=donate),
            )
        """,
    ),
}


@pytest.mark.parametrize("code", sorted(FIXTURES))
def test_rule_fires_and_stays_quiet(tmp_path, code):
    rel, firing, clean = FIXTURES[code]
    fired = active_codes(lint(tmp_path, rel, firing))
    assert code in fired, f"{code} did not fire on its firing fixture"
    assert active_codes(lint(tmp_path, "clean/" + rel, clean)) == [], (
        f"clean fixture for {code} raised findings"
    )


# TPL602/TPL603 are PROJECT-level (they need the manifest + docs as
# inputs, not just one module), so their firing+clean fixtures drive
# the project pass directly instead of analyze_file.
_ENTRY = {
    "module": "engine/runner.py", "name": "prefill",
    "static_argnums": [], "static_argnames": [],
    "partial_kwargs": [], "partial_pos": 0, "donate": True,
}


def _project_findings(tmp_path, sites, doc_text):
    from tools.tpulint import lattice

    doc = tmp_path / "ATTENTION.md"
    doc.write_text(doc_text)
    hits: list[tuple[str, str]] = []
    lattice.check_project(
        {"pkg/engine/runner.py": sites},
        lambda _p, _l, code, detail: hits.append((code, detail)),
        manifest={("engine/runner.py", "prefill"): dict(_ENTRY)},
        attention_doc=doc,
    )
    return [code for code, _ in hits]


PROJECT_FIXTURES = {"TPL602", "TPL603"}


def test_tpl602_stale_manifest_entry(tmp_path):
    # firing: the analyzed module has NO site for the manifest entry
    assert "TPL602" in _project_findings(tmp_path, [], "prefill doc")
    # clean: the site exists
    site = {**_ENTRY, "line": 1}
    assert _project_findings(tmp_path, [site], "prefill doc") == []


def test_tpl603_entry_missing_from_docs(tmp_path):
    site = {**_ENTRY, "line": 1}
    assert "TPL603" in _project_findings(
        tmp_path, [site], "no entry names here"
    )
    assert _project_findings(tmp_path, [site], "see `prefill`") == []


def test_fixture_table_covers_every_rule():
    assert sorted({*FIXTURES, *PROJECT_FIXTURES}) == sorted(
        lint_config.RULES
    )


# ----------------------------------------------------------- suppressions


def test_suppression_with_reason_suppresses(tmp_path):
    findings = lint(
        tmp_path, STEP_PATH,
        """
        import numpy as np
        def pull(packed_dev):
            return np.asarray(packed_dev)  # tpulint: disable=TPL202(one fetch per wave)
        """,
    )
    assert active_codes(findings) == []
    suppressed = [f for f in findings if f.suppressed]
    assert len(suppressed) == 1
    assert suppressed[0].code == "TPL202"
    assert suppressed[0].reason == "one fetch per wave"


def test_suppression_on_preceding_line(tmp_path):
    findings = lint(
        tmp_path, STEP_PATH,
        """
        import numpy as np
        def pull(packed_dev):
            # tpulint: disable=TPL202(statement too long for a trailing comment)
            return np.asarray(packed_dev)
        """,
    )
    assert active_codes(findings) == []


def test_reasonless_suppression_does_not_suppress(tmp_path):
    findings = lint(
        tmp_path, STEP_PATH,
        """
        import numpy as np
        def pull(packed_dev):
            return np.asarray(packed_dev)  # tpulint: disable=TPL202
        """,
    )
    codes = active_codes(findings)
    assert "TPL000" in codes  # the audit finding
    assert "TPL202" in codes  # and the original hazard still reported


def test_trailing_suppression_does_not_leak_to_next_line(tmp_path):
    """A trailing disable waives ONLY its own line — the hazard on the
    line below must still be reported."""
    findings = lint(
        tmp_path, STEP_PATH,
        """
        import numpy as np
        def pull(packed_dev, logits):
            a = np.asarray(packed_dev)  # tpulint: disable=TPL202(first line only)
            b = np.asarray(logits)
            return a, b
        """,
    )
    assert active_codes(findings) == ["TPL202"]
    assert [f for f in findings if f.suppressed][0].line < [
        f for f in findings if not f.suppressed
    ][0].line


def test_reason_may_contain_parentheses(tmp_path):
    findings = lint(
        tmp_path, STEP_PATH,
        """
        import numpy as np
        def pull(packed_dev):
            return np.asarray(packed_dev)  # tpulint: disable=TPL202(one fetch (per wave), by design)
        """,
    )
    assert active_codes(findings) == []
    assert [f for f in findings if f.suppressed][0].reason == (
        "one fetch (per wave), by design"
    )


def test_disable_marker_in_docstring_is_not_a_suppression(tmp_path):
    """Quoting the syntax in a docstring (as the docs do) must neither
    suppress anything nor raise TPL000."""
    findings = lint(
        tmp_path, STEP_PATH,
        '''
        """Docs: write `# tpulint: disable=TPL202` to waive a finding."""
        import numpy as np
        def pull(packed_dev):
            return np.asarray(packed_dev)
        """mid-module string: # tpulint: disable=TPL202"""
        ''',
    )
    assert active_codes(findings) == ["TPL202"]


def test_awaited_sync_io_names_are_exempt(tmp_path):
    findings = lint(
        tmp_path, ASYNC_PATH,
        """
        async def handler(aiopath):
            return await aiopath.read_text()
        """,
    )
    assert active_codes(findings) == []


def test_wrong_code_does_not_suppress(tmp_path):
    findings = lint(
        tmp_path, STEP_PATH,
        """
        import numpy as np
        def pull(packed_dev):
            return np.asarray(packed_dev)  # tpulint: disable=TPL201(wrong code)
        """,
    )
    assert "TPL202" in active_codes(findings)


# ------------------------------------------------------------ scope rules


def test_host_sync_rules_scoped_to_step_loop_modules(tmp_path):
    src = """
    import numpy as np
    def pull(packed_dev):
        return np.asarray(packed_dev), packed_dev.item()
    """
    assert active_codes(lint(tmp_path, "pkg/grpc/conv.py", src)) == []
    fired = active_codes(lint(tmp_path, "pkg/ops/kernels.py", src))
    assert set(fired) == {"TPL201", "TPL202"}


def test_registry_methods_are_jit_scoped(tmp_path):
    findings = lint(
        tmp_path, "pkg/models/llama.py",
        """
        class LlamaForCausalLM:
            def prefill(self, params, token_ids):
                if token_ids.shape[0] > 8:
                    return params
                return token_ids
        """,
    )
    assert active_codes(findings) == ["TPL101"]


# -------------------------------------------------------------- CLI gate


def test_cli_exit_codes(tmp_path, capsys):
    bad = tmp_path / "engine" / "runner.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import numpy as np\n"
        "def pull(packed_dev):\n"
        "    return np.asarray(packed_dev)\n"
    )
    assert tpulint_main([str(bad)]) == 1
    capsys.readouterr()
    assert tpulint_main([str(tmp_path / "missing.py")]) == 2
    assert tpulint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for code in lint_config.RULES:
        assert code in out


def test_shipped_package_is_tpulint_clean(capsys):
    """The acceptance gate: zero findings, zero unexplained suppressions
    on the shipped package AND the dettest harness (same invocation as
    ``nox -s tpulint``)."""
    rc = tpulint_main([
        str(REPO_ROOT / "vllm_tgis_adapter_tpu"),
        str(REPO_ROOT / "tools" / "dettest"),
    ])
    out = capsys.readouterr().out
    assert rc == 0, f"tpulint found hazards:\n{out}"


def test_docs_list_every_rule_code():
    """docs/STATIC_ANALYSIS.md ↔ rule-table drift gate (obs_check style)."""
    doc = (REPO_ROOT / "docs" / "STATIC_ANALYSIS.md").read_text()
    for code in lint_config.RULES:
        assert code in doc, f"{code} missing from docs/STATIC_ANALYSIS.md"
    assert "tpulint: disable=" in doc  # suppression syntax documented


# --------------------------------------------- historical bug shapes


def test_tpl502_detects_the_pr9_gcd_promotion_task(tmp_path):
    """The PR 9 bug shape verbatim: a transfer task spawned with a raw
    create_task and referenced nowhere strongly — the loop's weak ref
    lets GC collect it mid-flight, parking its request forever."""
    findings = lint(
        tmp_path, "pkg/engine/kv_tier.py",
        """
        import asyncio
        class Tier:
            def start_promotion(self, ticket, put_fn):
                loop = asyncio.get_running_loop()
                loop.create_task(self._assemble(ticket, put_fn))
        """,
    )
    assert "TPL502" in active_codes(findings)


def test_tpl511_batch_kind_with_request_id(tmp_path):
    """A batch-level kind (no per-request DFA edges) recorded WITH a
    request_id would enter the per-request stream the grammar
    deliberately excludes it from — the second TPL511 mode."""
    findings = lint(
        tmp_path, "pkg/engine/core.py",
        """
        def wave(self, rid):
            self.recorder.record("decode", request_id=rid, num_seqs=4)
        """,
    )
    assert "TPL511" in active_codes(findings)


def test_tpl512_undeclared_state_assignment(tmp_path):
    """A lifecycle assignment to a state the manifest never declared."""
    findings = lint(
        tmp_path, "pkg/supervisor/supervisor.py",
        """
        def corrupt(self):
            self.engine.lifecycle = "zombie"
        """,
    )
    assert "TPL512" in active_codes(findings)


def test_tpl512_symbolic_lifecycle_constants_resolve(tmp_path):
    """LIFECYCLE_* spellings resolve to their lowercase suffix, so the
    supervisor's symbolic transition sites are checked too."""
    findings = lint(
        tmp_path, "pkg/supervisor/supervisor.py",
        """
        from vllm_tgis_adapter_tpu.supervisor.lifecycle import (
            LIFECYCLE_DEAD,
            LIFECYCLE_SERVING,
        )
        from vllm_tgis_adapter_tpu.engine import sanitizer
        def resurrect(self):
            sanitizer.check_lifecycle_edge(LIFECYCLE_DEAD, LIFECYCLE_SERVING)
        """,
    )
    assert "TPL512" in active_codes(findings)


def test_tpl304_detects_the_pump_shape(tmp_path):
    """The PR 4 pump-hang shape: wait_for over an admission wake event
    that may already be set (bpo-42130 on py3.10)."""
    findings = lint(
        tmp_path, "pkg/frontdoor/admission.py",
        """
        import asyncio
        class FrontDoor:
            async def _pump(self):
                while True:
                    await asyncio.wait_for(self._wake.wait(), 0.25)
        """,
    )
    assert "TPL304" in active_codes(findings)


def test_tpl501_detects_the_unpaired_pin_shape(tmp_path):
    """The PR 5 bug shape: a pin whose release is skipped the moment
    the work between the pair raises (exception path leaks the ref)."""
    findings = lint(
        tmp_path, "pkg/engine/core.py",
        """
        def restart(self, seq):
            self.lora_manager.pin(seq.lora_name)
            self.replay(seq)          # raises on a wedged device
            self.lora_manager.unpin(seq.lora_name)
        """,
    )
    assert "TPL501" in active_codes(findings)


def test_tpl402_cross_module_cycle_via_project_pass(tmp_path):
    """Interprocedural, cross-module: module A holds its lock and calls
    into module B (which takes B's lock); module B holds its lock and
    calls back into A.  Neither file alone shows a cycle."""
    from tools.tpulint.analyzer import analyze_project

    a = tmp_path / "pkg" / "engine" / "alpha.py"
    b = tmp_path / "pkg" / "engine" / "beta.py"
    a.parent.mkdir(parents=True)
    a.write_text(textwrap.dedent(
        """
        import threading
        alpha_lock = threading.Lock()
        def touch_beta(beta):
            with alpha_lock:
                beta_side(beta)
        def alpha_side(x):
            with alpha_lock:
                pass
        """
    ))
    b.write_text(textwrap.dedent(
        """
        import threading
        beta_lock = threading.Lock()
        def beta_side(x):
            with beta_lock:
                pass
        def touch_alpha(alpha):
            with beta_lock:
                alpha_side(alpha)
        """
    ))
    findings = analyze_project([a, b], root=tmp_path)
    cross = [
        f for f in findings
        if f.code == "TPL402" and "cross-module" in f.message
    ]
    assert cross, [f.render() for f in findings]


def test_tpl501_second_unguarded_pair_still_fires(tmp_path):
    """A correctly finally-guarded pair must not whitelist a SECOND,
    unguarded acquire of the same names in the same function."""
    findings = lint(
        tmp_path, "pkg/engine/core.py",
        """
        def admit_two(self, a, b):
            self.lora_manager.pin(a.name)
            try:
                work(a)
            finally:
                self.lora_manager.unpin(a.name)
            self.lora_manager.pin(b.name)
            work(b)
            self.lora_manager.unpin(b.name)
        """,
    )
    assert "TPL501" in active_codes(findings)


def test_tpl402_cycle_through_recursive_helpers(tmp_path):
    """Lock closures must converge through call cycles: fa<->fb
    recurse, and a caller holding b_lock reaches a_lock only through
    that cycle.  A memoized partial expansion used to drop the edge."""
    findings = lint(
        tmp_path, "pkg/engine/core.py",
        """
        import threading
        a_lock = threading.Lock()
        b_lock = threading.Lock()
        def fa(n):
            with a_lock:
                pass
            fb(n)
        def fb(n):
            fa(n)
        def prime():
            fb(0)  # populate the closure cache via the cycle
        def under_b(n):
            with b_lock:
                fb(n)
        def under_a():
            with a_lock:
                with b_lock:
                    pass
        """,
    )
    assert "TPL402" in active_codes(findings)


def test_tpl402_multi_item_with_statement(tmp_path):
    """`with a_lock, b_lock:` acquires in item order and must emit the
    ordering edge exactly like two nested statements (the textbook
    two-lock deadlock must not escape via the one-statement spelling)."""
    findings = lint(
        tmp_path, "pkg/engine/core.py",
        """
        import threading
        a_lock = threading.Lock()
        b_lock = threading.Lock()
        def one():
            with a_lock, b_lock:
                pass
        def two():
            with b_lock, a_lock:
                pass
        """,
    )
    assert "TPL402" in active_codes(findings)


def test_tpl402_cycle_edges_attributed_to_one_module(tmp_path):
    """A cycle whose EDGES all attribute to one module can still be
    invisible to the per-file pass (the called functions live in
    another file) — the project pass must report it, deduping against
    per-file-REPORTED cycles, not edge attribution."""
    from tools.tpulint.analyzer import analyze_file as _af
    from tools.tpulint.analyzer import analyze_project

    a = tmp_path / "pkg" / "engine" / "alpha.py"
    b = tmp_path / "pkg" / "engine" / "beta.py"
    a.parent.mkdir(parents=True)
    a.write_text(textwrap.dedent(
        """
        def first(tier):
            with tier.x_lock:
                take_y(tier)
        def second(tier):
            with tier.y_lock:
                take_x(tier)
        """
    ))
    b.write_text(textwrap.dedent(
        """
        def take_y(tier):
            with tier.y_lock:
                pass
        def take_x(tier):
            with tier.x_lock:
                pass
        """
    ))
    # neither file alone shows the cycle...
    assert "TPL402" not in active_codes(_af(a, root=tmp_path))
    assert "TPL402" not in active_codes(_af(b, root=tmp_path))
    # ...so the project pass MUST
    findings = analyze_project([a, b], root=tmp_path)
    assert any(f.code == "TPL402" for f in findings), [
        f.render() for f in findings
    ]


def test_tpl601_manifest_entry_missing_optional_key_is_not_drift(tmp_path):
    """A hand-edited manifest entry without partial_pos must compare
    against describe_site's default (0), not a bogus []."""
    import ast as _ast

    from tools.tpulint import lattice

    entry = {
        "module": "engine/runner.py", "name": "prefill",
        "static_argnums": [], "static_argnames": [],
        "partial_kwargs": [], "donate": True,
        # no partial_pos key
    }
    src = textwrap.dedent(
        """
        import jax
        from vllm_tgis_adapter_tpu.compile_tracker import track_jit
        fn = track_jit("prefill", jax.jit(model.prefill,
                                          donate_argnums=(0,)))
        """
    )
    hits: list[str] = []
    lattice.check_module(
        _ast.parse(src), "pkg/engine/runner.py",
        lambda _n, code, _d="": hits.append(code),
        manifest={("engine/runner.py", "prefill"): entry},
    )
    assert hits == []


def test_tpl502_exemption_is_exact_component():
    """engine/io_utils.py must not inherit utils.py's exemption."""
    from tools.tpulint import config as cfg

    assert cfg.is_task_helper_module("vllm_tgis_adapter_tpu/utils.py")
    assert cfg.is_task_helper_module("utils.py")
    assert not cfg.is_task_helper_module(
        "vllm_tgis_adapter_tpu/engine/io_utils.py"
    )
    assert not cfg.is_task_helper_module("pkg/tgis_utils.py")


# ------------------------------------------- compile-lattice manifest


def test_checked_in_manifest_matches_the_package():
    """Drift gate: regenerating the manifest from the shipped package
    must reproduce the checked-in file byte-for-byte (entries)."""
    import json

    from tools.tpulint.lattice import build_manifest

    built = build_manifest([REPO_ROOT / "vllm_tgis_adapter_tpu"],
                           root=REPO_ROOT)
    checked_in = json.loads(
        (REPO_ROOT / "tools" / "tpulint" / "lattice_manifest.json")
        .read_text()
    )
    assert built["entries"] == checked_in["entries"], (
        "lattice_manifest.json is stale — regenerate with "
        "`python -m tools.tpulint --write-lattice` and update "
        "docs/ATTENTION.md"
    )


def test_write_lattice_round_trips(tmp_path):
    from tools.tpulint.lattice import write_manifest

    out = tmp_path / "manifest.json"
    target = write_manifest(
        [REPO_ROOT / "vllm_tgis_adapter_tpu"], out=out, root=REPO_ROOT
    )
    assert target == out
    import json

    doc = json.loads(out.read_text())
    names = {e["name"] for e in doc["entries"]}
    assert "ragged_step" in names and "lora_slot_update" in names


def test_manifest_agrees_with_live_engine_boot(tiny_model_dir):
    """Acceptance: every entry point the compile tracker OBSERVES on a
    live engine boot + serve matches a manifest name (fnmatch for the
    pipeline's pp{s}_* templates)."""
    import fnmatch
    import json

    from vllm_tgis_adapter_tpu import compile_tracker
    from vllm_tgis_adapter_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        LoRAConfig,
        ModelConfig,
        ParallelConfig,
        SchedulerConfig,
    )
    from vllm_tgis_adapter_tpu.engine.core import LLMEngine
    from vllm_tgis_adapter_tpu.engine.sampling_params import SamplingParams

    compile_tracker.reset()
    model_config = ModelConfig.from_pretrained(
        tiny_model_dir, dtype="float32"
    )
    config = EngineConfig(
        model_config=model_config,
        cache_config=CacheConfig(
            block_size=16, num_blocks=64,
            cache_dtype=model_config.dtype,
        ),
        scheduler_config=SchedulerConfig(
            max_num_seqs=4, prefill_buckets=(32, 64),
        ),
        parallel_config=ParallelConfig(),
        lora_config=LoRAConfig(),
    )
    engine = LLMEngine.from_config(config)
    engine.add_request(
        "live-boot", "hello lattice", SamplingParams(max_tokens=8)
    )
    for _ in range(200):
        if not engine.has_unfinished_requests():
            break
        engine.step()
    observed = {fn for fn, _shape in compile_tracker.shapes()}
    assert observed, "live boot compiled nothing — tracker broken?"
    manifest = json.loads(
        (REPO_ROOT / "tools" / "tpulint" / "lattice_manifest.json")
        .read_text()
    )
    patterns = [e["name"] for e in manifest["entries"]]
    unmatched = {
        fn for fn in observed
        if not any(fnmatch.fnmatch(fn, p) for p in patterns)
    }
    assert not unmatched, (
        f"live engine compiled entry points missing from the "
        f"compile-lattice manifest: {sorted(unmatched)}"
    )
