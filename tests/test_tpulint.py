"""tpulint analyzer tests: every rule code in both directions.

``FIXTURES`` maps each rule code to a (firing, clean) pair of snippet
modules; one parametrized test asserts the firing snippet raises exactly
that code and the clean snippet raises nothing.  Separate tests cover the
suppression contract (reasoned disables suppress, reason-less disables
are TPL000) and the self-check: the shipped package must be
tpulint-clean (exit 0) with zero unexplained suppressions.
"""

from __future__ import annotations

import sys
import textwrap
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from tools.tpulint import config as lint_config  # noqa: E402
from tools.tpulint.analyzer import analyze_file  # noqa: E402
from tools.tpulint.cli import main as tpulint_main  # noqa: E402

STEP_PATH = "pkg/engine/runner.py"  # classified as step-loop
ASYNC_PATH = "pkg/grpc/server.py"  # any module; rules key off async def


def lint(tmp_path: Path, rel: str, source: str):
    """Write ``source`` at ``rel`` under tmp_path and analyze it."""
    target = tmp_path / rel
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text(textwrap.dedent(source))
    return analyze_file(target, root=tmp_path)


def active_codes(findings) -> list[str]:
    return [f.code for f in findings if not f.suppressed]


# --------------------------------------------------------------- fixtures

FIXTURES: dict[str, tuple[str, str, str]] = {
    # code: (path, firing snippet, clean snippet)
    "TPL000": (
        STEP_PATH,
        """
        import numpy as np
        def pull(packed_dev):
            return np.asarray(packed_dev)  # tpulint: disable=TPL202
        """,
        """
        import numpy as np
        def pull(packed_dev):
            return np.asarray(packed_dev)  # tpulint: disable=TPL202(one sanctioned fetch)
        """,
    ),
    "TPL101": (
        STEP_PATH,
        """
        import jax
        @jax.jit
        def f(x, n):
            if x.shape[0] > n:
                return x
            return x * 2
        """,
        """
        import jax
        from functools import partial
        @partial(jax.jit, static_argnames=("n",))
        def f(x, n=4):
            if n > 2:
                return x
            if x is None:
                return x
            return x * 2
        """,
    ),
    "TPL102": (
        STEP_PATH,
        """
        import jax
        @jax.jit
        def f(x, table):
            return table[f"bucket-{x.shape[0]}"]
        """,
        """
        import jax
        @jax.jit
        def f(x, table):
            if x is None:
                raise ValueError(f"bad shape {x.shape}")
            return table["bucket"]
        """,
    ),
    "TPL103": (
        STEP_PATH,
        """
        import jax
        def g(x, num_steps: int, flashy: bool = True):
            return x
        fn = jax.jit(g)
        """,
        """
        import jax
        def g(x, num_steps: int, flashy: bool = True):
            return x
        fn = jax.jit(g, static_argnums=(1,), static_argnames=("flashy",))
        """,
    ),
    "TPL104": (
        STEP_PATH,
        """
        import jax
        def build(model):
            return jax.jit(model.decode)
        """,
        """
        import jax
        def build(model, sh):
            a = jax.jit(model.decode, donate_argnums=(1,))
            b = jax.jit(lambda: model.make_kv_caches(8), out_shardings=sh)
            c = jax.jit(model.propose)
            return a, b, c
        """,
    ),
    "TPL201": (
        STEP_PATH,
        """
        import jax
        def step(x):
            x.block_until_ready()
            return x[0].item() + jax.device_get(x)[1]
        """,
        """
        import jax
        def step(x):
            return x[0] + x[1]
        """,
    ),
    "TPL202": (
        STEP_PATH,
        """
        import numpy as np
        def pull(packed_dev, logits):
            return np.asarray(packed_dev), float(logits[0])
        """,
        """
        import numpy as np
        def host_prep(rows, slots):
            return np.asarray(rows), np.asarray([1, 2]), int(slots[0])
        """,
    ),
    "TPL301": (
        ASYNC_PATH,
        """
        import time
        async def handler():
            time.sleep(0.1)
        """,
        """
        import asyncio, time
        async def handler():
            await asyncio.sleep(0.1)
        def sync_helper():
            time.sleep(0.1)
        """,
    ),
    "TPL302": (
        ASYNC_PATH,
        """
        from pathlib import Path
        async def handler(path):
            with open(path) as f:
                pass
            return Path(path).read_text()
        """,
        """
        import asyncio
        from pathlib import Path
        def _read(path):
            with open(path) as f:
                return f.read()
        async def handler(path):
            def inner():
                return Path(path).read_text()
            return await asyncio.to_thread(_read, path)
        """,
    ),
    "TPL303": (
        ASYNC_PATH,
        """
        async def loop(engine, plan):
            return engine.wait_step(plan)
        """,
        """
        import asyncio
        async def loop(engine, plan):
            await engine.precompile("all")
            return await asyncio.to_thread(engine.wait_step, plan)
        """,
    ),
}


@pytest.mark.parametrize("code", sorted(lint_config.RULES))
def test_rule_fires_and_stays_quiet(tmp_path, code):
    rel, firing, clean = FIXTURES[code]
    fired = active_codes(lint(tmp_path, rel, firing))
    assert code in fired, f"{code} did not fire on its firing fixture"
    assert active_codes(lint(tmp_path, "clean/" + rel, clean)) == [], (
        f"clean fixture for {code} raised findings"
    )


def test_fixture_table_covers_every_rule():
    assert sorted(FIXTURES) == sorted(lint_config.RULES)


# ----------------------------------------------------------- suppressions


def test_suppression_with_reason_suppresses(tmp_path):
    findings = lint(
        tmp_path, STEP_PATH,
        """
        import numpy as np
        def pull(packed_dev):
            return np.asarray(packed_dev)  # tpulint: disable=TPL202(one fetch per wave)
        """,
    )
    assert active_codes(findings) == []
    suppressed = [f for f in findings if f.suppressed]
    assert len(suppressed) == 1
    assert suppressed[0].code == "TPL202"
    assert suppressed[0].reason == "one fetch per wave"


def test_suppression_on_preceding_line(tmp_path):
    findings = lint(
        tmp_path, STEP_PATH,
        """
        import numpy as np
        def pull(packed_dev):
            # tpulint: disable=TPL202(statement too long for a trailing comment)
            return np.asarray(packed_dev)
        """,
    )
    assert active_codes(findings) == []


def test_reasonless_suppression_does_not_suppress(tmp_path):
    findings = lint(
        tmp_path, STEP_PATH,
        """
        import numpy as np
        def pull(packed_dev):
            return np.asarray(packed_dev)  # tpulint: disable=TPL202
        """,
    )
    codes = active_codes(findings)
    assert "TPL000" in codes  # the audit finding
    assert "TPL202" in codes  # and the original hazard still reported


def test_trailing_suppression_does_not_leak_to_next_line(tmp_path):
    """A trailing disable waives ONLY its own line — the hazard on the
    line below must still be reported."""
    findings = lint(
        tmp_path, STEP_PATH,
        """
        import numpy as np
        def pull(packed_dev, logits):
            a = np.asarray(packed_dev)  # tpulint: disable=TPL202(first line only)
            b = np.asarray(logits)
            return a, b
        """,
    )
    assert active_codes(findings) == ["TPL202"]
    assert [f for f in findings if f.suppressed][0].line < [
        f for f in findings if not f.suppressed
    ][0].line


def test_reason_may_contain_parentheses(tmp_path):
    findings = lint(
        tmp_path, STEP_PATH,
        """
        import numpy as np
        def pull(packed_dev):
            return np.asarray(packed_dev)  # tpulint: disable=TPL202(one fetch (per wave), by design)
        """,
    )
    assert active_codes(findings) == []
    assert [f for f in findings if f.suppressed][0].reason == (
        "one fetch (per wave), by design"
    )


def test_disable_marker_in_docstring_is_not_a_suppression(tmp_path):
    """Quoting the syntax in a docstring (as the docs do) must neither
    suppress anything nor raise TPL000."""
    findings = lint(
        tmp_path, STEP_PATH,
        '''
        """Docs: write `# tpulint: disable=TPL202` to waive a finding."""
        import numpy as np
        def pull(packed_dev):
            return np.asarray(packed_dev)
        """mid-module string: # tpulint: disable=TPL202"""
        ''',
    )
    assert active_codes(findings) == ["TPL202"]


def test_awaited_sync_io_names_are_exempt(tmp_path):
    findings = lint(
        tmp_path, ASYNC_PATH,
        """
        async def handler(aiopath):
            return await aiopath.read_text()
        """,
    )
    assert active_codes(findings) == []


def test_wrong_code_does_not_suppress(tmp_path):
    findings = lint(
        tmp_path, STEP_PATH,
        """
        import numpy as np
        def pull(packed_dev):
            return np.asarray(packed_dev)  # tpulint: disable=TPL201(wrong code)
        """,
    )
    assert "TPL202" in active_codes(findings)


# ------------------------------------------------------------ scope rules


def test_host_sync_rules_scoped_to_step_loop_modules(tmp_path):
    src = """
    import numpy as np
    def pull(packed_dev):
        return np.asarray(packed_dev), packed_dev.item()
    """
    assert active_codes(lint(tmp_path, "pkg/grpc/conv.py", src)) == []
    fired = active_codes(lint(tmp_path, "pkg/ops/kernels.py", src))
    assert set(fired) == {"TPL201", "TPL202"}


def test_registry_methods_are_jit_scoped(tmp_path):
    findings = lint(
        tmp_path, "pkg/models/llama.py",
        """
        class LlamaForCausalLM:
            def prefill(self, params, token_ids):
                if token_ids.shape[0] > 8:
                    return params
                return token_ids
        """,
    )
    assert active_codes(findings) == ["TPL101"]


# -------------------------------------------------------------- CLI gate


def test_cli_exit_codes(tmp_path, capsys):
    bad = tmp_path / "engine" / "runner.py"
    bad.parent.mkdir(parents=True)
    bad.write_text(
        "import numpy as np\n"
        "def pull(packed_dev):\n"
        "    return np.asarray(packed_dev)\n"
    )
    assert tpulint_main([str(bad)]) == 1
    capsys.readouterr()
    assert tpulint_main([str(tmp_path / "missing.py")]) == 2
    assert tpulint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for code in lint_config.RULES:
        assert code in out


def test_shipped_package_is_tpulint_clean(capsys):
    """The acceptance gate: zero findings, zero unexplained suppressions
    on the shipped package (same invocation as ``nox -s tpulint``)."""
    rc = tpulint_main([str(REPO_ROOT / "vllm_tgis_adapter_tpu")])
    out = capsys.readouterr().out
    assert rc == 0, f"tpulint found hazards:\n{out}"


def test_docs_list_every_rule_code():
    """docs/STATIC_ANALYSIS.md ↔ rule-table drift gate (obs_check style)."""
    doc = (REPO_ROOT / "docs" / "STATIC_ANALYSIS.md").read_text()
    for code in lint_config.RULES:
        assert code in doc, f"{code} missing from docs/STATIC_ANALYSIS.md"
    assert "tpulint: disable=" in doc  # suppression syntax documented
