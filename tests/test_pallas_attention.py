"""Pallas kernel parity vs the XLA reference attention (interpreter mode).

The XLA implementations in ops/attention.py are the numerical ground
truth; the Pallas kernels must match them bit-for-shape on every backend.
On CPU CI the kernels run through the Pallas interpreter; on TPU the same
code compiles through Mosaic.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from vllm_tgis_adapter_tpu.ops import attention as ref_ops
from vllm_tgis_adapter_tpu.ops import pallas_attention as pk


def make_paged_case(seed, b, num_kv, g, head_dim, block_size, max_blocks,
                    num_slots, dtype=np.float32):
    """Shared paged-decode test case builder (also used by the on-hardware
    gate in test_tpu_kernels.py — one construction, two suites)."""
    rng = np.random.default_rng(seed)
    h = num_kv * g
    q = rng.standard_normal((b, h, head_dim)).astype(dtype)
    # head-leading cache layout (ops/pallas_attention.py docstring)
    k_cache = rng.standard_normal((num_kv, num_slots, head_dim)).astype(dtype)
    v_cache = rng.standard_normal((num_kv, num_slots, head_dim)).astype(dtype)
    # distinct random pages per sequence, random context lengths
    pages = rng.permutation(num_slots // block_size)[: b * max_blocks]
    block_tables = pages.reshape(b, max_blocks).astype(np.int32)
    context_lens = rng.integers(
        1, max_blocks * block_size + 1, size=b
    ).astype(np.int32)
    return q, k_cache, v_cache, block_tables, context_lens


def ragged_decode_pallas(q, k_cache, v_cache, block_tables, context_lens,
                         block_size, scale, *, window=0, alibi_slopes=None):
    """Serving decode through the RAGGED Pallas kernel (interpret mode):
    each batch row is a one-token span — the formulation that replaced
    the retired folded/perhead decode kernels (docs/ATTENTION.md)."""
    from vllm_tgis_adapter_tpu.ops import ragged_attention as R

    b = int(np.asarray(q).shape[0])
    pos = jnp.maximum(jnp.asarray(context_lens, jnp.int32), 1) - 1
    starts = jnp.arange(b + 1, dtype=jnp.int32)
    block_q = min(8, R._pow2_ceil(b))
    work = R.dense_work_schedule(
        pos, jnp.asarray(block_tables, jnp.int32),
        block_size=block_size, block_q=block_q,
        t_pad=-(-b // block_q) * block_q,
    )
    return R._ragged_attention_pallas(
        jnp.asarray(q), jnp.asarray(k_cache), jnp.asarray(v_cache),
        starts, pos, work, block_size, scale, block_q=block_q,
        window=window, alibi_slopes=alibi_slopes, interpret=True,
    )


@pytest.mark.parametrize("seed", [0, 1])
@pytest.mark.parametrize("g", [1, 4])
def test_ragged_decode_matches_reference(seed, g):
    b, num_kv, head_dim, block_size, max_blocks = 5, 2, 64, 16, 4
    q, k_cache, v_cache, bt, cl = make_paged_case(
        seed, b, num_kv, g, head_dim, block_size, max_blocks, num_slots=512
    )
    scale = head_dim**-0.5
    ref = ref_ops.paged_decode_attention_xla(
        jnp.asarray(q), jnp.asarray(k_cache), jnp.asarray(v_cache),
        jnp.asarray(bt), jnp.asarray(cl), block_size, scale,
    )
    got = ragged_decode_pallas(q, k_cache, v_cache, bt, cl, block_size,
                               scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ragged_decode_short_context_ignores_garbage_pages():
    """Pages beyond context_len must not leak into the output even when
    the block table rows carry arbitrary ids there."""
    b, num_kv, g, head_dim, block_size, max_blocks = 2, 2, 2, 64, 16, 4
    q, k_cache, v_cache, bt, _ = make_paged_case(
        7, b, num_kv, g, head_dim, block_size, max_blocks, num_slots=256
    )
    cl = np.asarray([3, 17], np.int32)  # partial first / second page
    bt_garbage = bt.copy()
    bt_garbage[0, 1:] = 999999  # ids far out of range
    bt_garbage[1, 2:] = -1
    scale = head_dim**-0.5
    ref = ref_ops.paged_decode_attention_xla(
        jnp.asarray(q), jnp.asarray(k_cache), jnp.asarray(v_cache),
        jnp.asarray(bt), jnp.asarray(cl), block_size, scale,
    )
    got = ragged_decode_pallas(q, k_cache, v_cache, bt_garbage, cl,
                               block_size, scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("t,valid", [(128, 128), (128, 100), (256, 33)])
@pytest.mark.parametrize("g", [1, 4])
def test_flash_prefill_matches_reference(t, valid, g):
    num_kv, head_dim = 2, 64
    h = num_kv * g
    rng = np.random.default_rng(t + valid + g)
    q = rng.standard_normal((t, h, head_dim), dtype=np.float32)
    k = rng.standard_normal((t, num_kv, head_dim), dtype=np.float32)
    v = rng.standard_normal((t, num_kv, head_dim), dtype=np.float32)
    scale = head_dim**-0.5
    ref = ref_ops.prefill_attention_xla(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), scale,
        jnp.asarray(valid),
    )
    got = pk.prefill_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), scale,
        jnp.asarray(valid, jnp.int32),
        block_q=64, block_k=64, interpret=True,
    )
    # only rows the engine consumes (real tokens) must match
    np.testing.assert_allclose(
        np.asarray(got)[:valid], np.asarray(ref)[:valid],
        rtol=2e-5, atol=2e-5,
    )


def test_flash_prefill_bf16():
    t, num_kv, g, head_dim = 128, 2, 2, 64
    h = num_kv * g
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((t, h, head_dim)), jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((t, num_kv, head_dim)), jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((t, num_kv, head_dim)), jnp.bfloat16)
    scale = head_dim**-0.5
    ref = ref_ops.prefill_attention_xla(q, k, v, scale, jnp.asarray(t))
    got = pk.prefill_attention(q, k, v, scale, jnp.asarray(t, jnp.int32),
                               interpret=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32),
        rtol=5e-2, atol=5e-2,
    )


def test_engine_end_to_end_with_pallas_backend(tiny_model_dir, monkeypatch):
    """Full engine slice with the Pallas kernels forced (interpreter on
    CPU): prefill writes pages, fused multi-step decode reads them."""
    monkeypatch.setenv("ATTENTION_BACKEND", "pallas")
    from vllm_tgis_adapter_tpu.engine.config import (
        CacheConfig,
        EngineConfig,
        LoRAConfig,
        ModelConfig,
        ParallelConfig,
        SchedulerConfig,
    )
    from vllm_tgis_adapter_tpu.engine.core import LLMEngine
    from vllm_tgis_adapter_tpu.engine.sampling_params import SamplingParams

    mcfg = ModelConfig.from_pretrained(tiny_model_dir, dtype="float32")
    config = EngineConfig(
        model_config=mcfg,
        cache_config=CacheConfig(block_size=16, num_blocks=32,
                                 cache_dtype=mcfg.dtype),
        scheduler_config=SchedulerConfig(
            max_num_seqs=2, prefill_buckets=(32,), num_decode_steps=2),
        parallel_config=ParallelConfig(),
        lora_config=LoRAConfig(),
    )
    engine = LLMEngine.from_config(config)
    engine.add_request("p", "hello world", SamplingParams(
        temperature=0.0, max_tokens=4, ignore_eos=True))
    outs = []
    for _ in range(50):
        if not engine.has_unfinished_requests():
            break
        outs.extend(engine.step())
    assert outs and len(outs[-1].outputs[0].token_ids) == 4


def test_pallas_kernels_under_tp_mesh(monkeypatch):
    """shard_map-wrapped kernels over the head-sharded TP mesh must match
    the unsharded XLA reference (each shard reads only local heads)."""
    from vllm_tgis_adapter_tpu.ops import attention as attn
    from vllm_tgis_adapter_tpu.parallel import build_mesh

    monkeypatch.setenv("ATTENTION_BACKEND", "pallas")
    b, num_kv, g, head_dim, block_size, max_blocks = 3, 4, 2, 64, 16, 4
    q, k_cache, v_cache, bt, cl = make_paged_case(
        3, b, num_kv, g, head_dim, block_size, max_blocks, num_slots=512
    )
    scale = head_dim**-0.5
    ref = attn.paged_decode_attention_xla(
        jnp.asarray(q), jnp.asarray(k_cache), jnp.asarray(v_cache),
        jnp.asarray(bt), jnp.asarray(cl), block_size, scale,
    )

    mesh = build_mesh(tensor_parallel_size=4)
    # decode through the serving ragged kernel (one-token spans), mesh
    # shard_map over the head axis
    from vllm_tgis_adapter_tpu.ops import ragged_attention as R

    pos = jnp.maximum(jnp.asarray(cl, jnp.int32), 1) - 1
    got = R.ragged_paged_attention(
        jnp.asarray(q), jnp.asarray(k_cache), jnp.asarray(v_cache),
        pos, jnp.arange(b + 1, dtype=jnp.int32), pos,
        jnp.asarray(b, jnp.int32), jnp.asarray(bt), block_size, scale,
        mesh=mesh,
    )
    # prefill too
    t, valid = 128, 100
    rng = np.random.default_rng(5)
    qp = rng.standard_normal((t, num_kv * g, head_dim), dtype=np.float32)
    kp = rng.standard_normal((t, num_kv, head_dim), dtype=np.float32)
    vp = rng.standard_normal((t, num_kv, head_dim), dtype=np.float32)
    ref_p = attn.prefill_attention_xla(
        jnp.asarray(qp), jnp.asarray(kp), jnp.asarray(vp), scale,
        jnp.asarray(valid),
    )
    got_p = attn.prefill_attention(
        jnp.asarray(qp), jnp.asarray(kp), jnp.asarray(vp), scale,
        jnp.asarray(valid, jnp.int32), mesh=mesh,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(got_p)[:valid],
                               np.asarray(ref_p)[:valid],
                               rtol=2e-5, atol=2e-5)


# ------------------------------------------------------------ chunked prefill


def make_chunk_case(seed, t, valid, start, num_kv, g, head_dim, block_size,
                    dtype=np.float32):
    """A chunk of queries + a cache holding start+valid tokens of context
    at the block table's pages (rest of the cache is noise)."""
    rng = np.random.default_rng(seed)
    total = start + valid
    max_blocks = -(-max(total, 1) // block_size) + 2
    num_slots = max(512, (max_blocks + 4) * block_size)
    q = rng.standard_normal((t, num_kv * g, head_dim)).astype(dtype)
    k_cache = rng.standard_normal((num_kv, num_slots, head_dim)).astype(dtype)
    v_cache = rng.standard_normal((num_kv, num_slots, head_dim)).astype(dtype)
    table = rng.permutation(num_slots // block_size)[:max_blocks].astype(
        np.int32
    )
    return q, k_cache, v_cache, table


@pytest.mark.parametrize("t,valid,start", [
    (64, 64, 128),   # full chunk, deep context
    (64, 33, 48),    # ragged chunk, unaligned start
    (128, 100, 0),   # first chunk (no prior context)
    (32, 32, 7),     # start not page-aligned
])
@pytest.mark.parametrize("g", [1, 4])
def test_chunked_prefill_kernel_matches_decode_formulation(t, valid, start, g):
    num_kv, head_dim, block_size = 2, 64, 16
    q, kc, vc, table = make_chunk_case(
        t + valid + start, t, valid, start, num_kv, g, head_dim, block_size
    )
    scale = head_dim**-0.5

    # ground truth: each query as a decode row with context pos+1
    local = np.arange(t)
    positions = start + local
    ctx = np.where(local < valid, positions + 1, 1).astype(np.int32)
    tables = np.broadcast_to(table[None, :], (t, table.shape[0]))
    ref = ref_ops.paged_decode_attention_xla(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc),
        jnp.asarray(tables), jnp.asarray(ctx), block_size, scale,
    )

    got = pk.chunked_prefill_attention(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc),
        jnp.asarray(table), jnp.asarray(start, jnp.int32),
        jnp.asarray(valid, jnp.int32), block_size, scale,
        block_q=32, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got)[:valid], np.asarray(ref)[:valid],
        rtol=2e-5, atol=2e-5,
    )


def test_chunked_prefill_dispatch_under_tp_mesh(monkeypatch):
    """shard_map-wrapped chunk kernel over the head-sharded mesh matches
    the unsharded fallback."""
    from vllm_tgis_adapter_tpu.ops import attention as attn
    from vllm_tgis_adapter_tpu.parallel import build_mesh

    num_kv, g, head_dim, block_size = 4, 2, 64, 16
    t, valid, start = 64, 50, 32
    q, kc, vc, table = make_chunk_case(
        9, t, valid, start, num_kv, g, head_dim, block_size
    )
    scale = head_dim**-0.5
    monkeypatch.setenv("ATTENTION_BACKEND", "xla")
    ref = attn.chunked_prefill_attention(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc),
        jnp.asarray(table), jnp.asarray(start), jnp.asarray(valid),
        block_size, scale,
    )
    monkeypatch.setenv("ATTENTION_BACKEND", "pallas")
    mesh = build_mesh(tensor_parallel_size=4)
    got = attn.chunked_prefill_attention(
        jnp.asarray(q), jnp.asarray(kc), jnp.asarray(vc),
        jnp.asarray(table), jnp.asarray(start), jnp.asarray(valid),
        block_size, scale, mesh=mesh,
    )
    np.testing.assert_allclose(
        np.asarray(got)[:valid], np.asarray(ref)[:valid],
        rtol=2e-5, atol=2e-5,
    )


# ------------------------------------------------------- sliding window

@pytest.mark.parametrize("window", [8, 24])
@pytest.mark.parametrize("g", [1, 4])
def test_windowed_ragged_decode_matches_reference(window, g):
    """Band-masked ragged decode vs the XLA windowed reference."""
    b, num_kv, head_dim, block_size, max_blocks = 5, 2, 64, 16, 4
    q, k_cache, v_cache, bt, cl = make_paged_case(
        3, b, num_kv, g, head_dim, block_size, max_blocks, num_slots=512
    )
    scale = head_dim**-0.5
    ref = ref_ops.paged_decode_attention_xla(
        jnp.asarray(q), jnp.asarray(k_cache), jnp.asarray(v_cache),
        jnp.asarray(bt), jnp.asarray(cl), block_size, scale, window=window,
    )
    got = ragged_decode_pallas(q, k_cache, v_cache, bt, cl, block_size,
                               scale, window=window)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("t,valid,window", [
    (128, 128, 16),   # band cuts deep into the prompt
    (128, 100, 16),   # + padding region
    (256, 256, 200),  # window wider than most rows' context
    (64, 64, 1),      # degenerate: attend to self only
])
def test_windowed_flash_prefill_matches_reference(t, valid, window):
    rng = np.random.default_rng(7)
    num_kv, g, head_dim = 2, 2, 32
    h = num_kv * g
    q = rng.standard_normal((t, h, head_dim)).astype(np.float32)
    k = rng.standard_normal((t, num_kv, head_dim)).astype(np.float32)
    v = rng.standard_normal((t, num_kv, head_dim)).astype(np.float32)
    scale = head_dim**-0.5
    ref = ref_ops.prefill_attention_xla(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), scale,
        jnp.asarray(valid), window=window,
    )
    got = pk.prefill_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), scale,
        jnp.asarray(valid, dtype=jnp.int32), window=window, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got)[:valid], np.asarray(ref)[:valid],
        rtol=2e-5, atol=2e-5,
    )


@pytest.mark.parametrize("t,valid,start,window", [
    (64, 64, 64, 16),   # later chunk, band inside prior context
    (64, 40, 100, 8),   # ragged chunk deep in context
    (32, 32, 0, 48),    # first chunk, window wider than the chunk
])
def test_windowed_chunked_prefill_matches_reference(t, valid, start, window):
    """Band-masked chunked kernel vs the windowed decode formulation."""
    rng = np.random.default_rng(11)
    num_kv, g, head_dim, block_size = 2, 2, 32, 16
    h = num_kv * g
    total = start + t
    max_blocks = -(-total // block_size) + 2
    num_slots = 1024
    q = rng.standard_normal((t, h, head_dim)).astype(np.float32)
    k_cache = rng.standard_normal(
        (num_kv, num_slots, head_dim)).astype(np.float32)
    v_cache = rng.standard_normal(
        (num_kv, num_slots, head_dim)).astype(np.float32)
    table = rng.permutation(num_slots // block_size)[:max_blocks].astype(
        np.int32
    )

    # reference: each chunk query as a decode row with a banded context
    local = np.arange(t)
    positions = start + local
    ctx = np.where(local < valid, positions + 1, 1).astype(np.int32)
    tables = np.broadcast_to(table, (t, max_blocks))
    ref = ref_ops.paged_decode_attention_xla(
        jnp.asarray(q), jnp.asarray(k_cache), jnp.asarray(v_cache),
        jnp.asarray(tables), jnp.asarray(ctx),
        block_size, head_dim**-0.5, window=window,
    )
    got = pk.chunked_prefill_attention(
        jnp.asarray(q), jnp.asarray(k_cache), jnp.asarray(v_cache),
        jnp.asarray(table), jnp.asarray(start, jnp.int32),
        jnp.asarray(valid, jnp.int32), block_size, head_dim**-0.5,
        window=window, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got)[:valid], np.asarray(ref)[:valid],
        rtol=2e-5, atol=2e-5,
    )


# ---------------------------------------------------------------- alibi

def _slopes(h):
    from vllm_tgis_adapter_tpu.models.llama import alibi_slopes
    return jnp.asarray(alibi_slopes(h), jnp.float32)


@pytest.mark.parametrize("g", [1, 4])
def test_alibi_ragged_decode_matches_reference(g):
    b, num_kv, head_dim, block_size, max_blocks = 5, 2, 64, 16, 4
    q, k_cache, v_cache, bt, cl = make_paged_case(
        13, b, num_kv, g, head_dim, block_size, max_blocks, num_slots=512
    )
    scale = head_dim**-0.5
    slopes = _slopes(num_kv * g)
    ref = ref_ops.paged_decode_attention_xla(
        jnp.asarray(q), jnp.asarray(k_cache), jnp.asarray(v_cache),
        jnp.asarray(bt), jnp.asarray(cl), block_size, scale,
        alibi_slopes=slopes,
    )
    got = ragged_decode_pallas(q, k_cache, v_cache, bt, cl, block_size,
                               scale, alibi_slopes=slopes)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("t,valid", [(128, 128), (256, 200)])
def test_alibi_flash_prefill_matches_reference(t, valid):
    rng = np.random.default_rng(17)
    num_kv, g, head_dim = 2, 2, 32
    h = num_kv * g
    q = rng.standard_normal((t, h, head_dim)).astype(np.float32)
    k = rng.standard_normal((t, num_kv, head_dim)).astype(np.float32)
    v = rng.standard_normal((t, num_kv, head_dim)).astype(np.float32)
    scale = head_dim**-0.5
    slopes = _slopes(h)
    ref = ref_ops.prefill_attention_xla(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), scale,
        jnp.asarray(valid), alibi_slopes=slopes,
    )
    got = pk.prefill_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), scale,
        jnp.asarray(valid, dtype=jnp.int32), alibi_slopes=slopes,
        interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(got)[:valid], np.asarray(ref)[:valid],
        rtol=2e-5, atol=2e-5,
    )


def test_alibi_chunked_prefill_matches_reference():
    rng = np.random.default_rng(19)
    num_kv, g, head_dim, block_size = 2, 2, 32, 16
    h = num_kv * g
    t, start = 64, 64
    max_blocks = -(-(start + t) // block_size) + 2
    num_slots = 1024
    q = rng.standard_normal((t, h, head_dim)).astype(np.float32)
    k_cache = rng.standard_normal(
        (num_kv, num_slots, head_dim)).astype(np.float32)
    v_cache = rng.standard_normal(
        (num_kv, num_slots, head_dim)).astype(np.float32)
    table = rng.permutation(num_slots // block_size)[:max_blocks].astype(
        np.int32
    )
    slopes = _slopes(h)

    local = np.arange(t)
    ctx = (start + local + 1).astype(np.int32)
    tables = np.broadcast_to(table, (t, max_blocks))
    ref = ref_ops.paged_decode_attention_xla(
        jnp.asarray(q), jnp.asarray(k_cache), jnp.asarray(v_cache),
        jnp.asarray(tables), jnp.asarray(ctx),
        block_size, head_dim**-0.5, alibi_slopes=slopes,
    )
    got = pk.chunked_prefill_attention(
        jnp.asarray(q), jnp.asarray(k_cache), jnp.asarray(v_cache),
        jnp.asarray(table), jnp.asarray(start, jnp.int32),
        jnp.asarray(t, jnp.int32), block_size, head_dim**-0.5,
        alibi_slopes=slopes, interpret=True,
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ragged_decode_fp8_cache_matches_reference():
    """--kv-cache-dtype float8_e4m3 through the ragged Pallas kernel:
    the cache stores f8, the kernel casts to f32 on read — parity with
    the XLA formulation on the same quantized cache (the on-chip Mosaic
    gate for this dtype rides tests/test_tpu_kernels.py)."""
    b, num_kv, g, head_dim, block_size, max_blocks = 4, 2, 2, 64, 16, 4
    q, k_cache, v_cache, bt, cl = make_paged_case(
        0, b, num_kv, g, head_dim, block_size, max_blocks, num_slots=512
    )
    kc8 = jnp.asarray(k_cache).astype(jnp.float8_e4m3fn)
    vc8 = jnp.asarray(v_cache).astype(jnp.float8_e4m3fn)
    scale = head_dim**-0.5
    ref = ref_ops.paged_decode_attention_xla(
        jnp.asarray(q), kc8, vc8,
        jnp.asarray(bt), jnp.asarray(cl), block_size, scale,
    )
    got = ragged_decode_pallas(q, kc8, vc8, bt, cl, block_size, scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize(
    "window,alibi,g",
    [(0, False, 4), (0, False, 1), (24, False, 2), (0, True, 2)],
)
def test_ragged_decode_mask_combinations_match(window, alibi, g):
    """Window/ALiBi/GQA combinations through the ONE serving decode
    kernel (ragged) — the grid the retired folded/perhead variants used
    to cover."""
    b, num_kv, head_dim, block_size, max_blocks = 4, 2, 64, 16, 4
    q, k_cache, v_cache, bt, cl = make_paged_case(
        3, b, num_kv, g, head_dim, block_size, max_blocks, num_slots=512
    )
    h = num_kv * g
    slopes = (
        jnp.asarray(np.geomspace(0.5, 0.004, h), jnp.float32)
        if alibi else None
    )
    scale = head_dim**-0.5
    ref = ref_ops.paged_decode_attention_xla(
        jnp.asarray(q), jnp.asarray(k_cache), jnp.asarray(v_cache),
        jnp.asarray(bt), jnp.asarray(cl), block_size, scale,
        window=window, alibi_slopes=slopes,
    )
    got = ragged_decode_pallas(q, k_cache, v_cache, bt, cl, block_size,
                               scale, window=window, alibi_slopes=slopes)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-5,
    )


def test_decode_kernel_ladder_is_retired():
    """The folded/perhead/xla decode variant ladder is GONE: neither the
    ops dispatcher nor the Pallas module exposes it, and the runner has
    no retry chain — a lowering failure is a real error, not a silent
    slow-path fallback (docs/ATTENTION.md)."""
    from vllm_tgis_adapter_tpu.engine.runner import ModelRunner

    for name in ("paged_decode_attention", "decode_kernel_variant",
                 "degrade_decode_kernel", "reset_decode_kernel",
                 "is_kernel_lowering_error"):
        assert not hasattr(ref_ops, name), name
    assert not hasattr(pk, "paged_decode_attention")
    assert not hasattr(ModelRunner, "_decode_kernel_retry")


def test_ragged_decode_single_row():
    """b=1 decode (the narrowest serving shape) through the ragged
    kernel."""
    b, num_kv, g, head_dim, block_size, max_blocks = 1, 2, 2, 64, 16, 4
    q, k_cache, v_cache, bt, cl = make_paged_case(
        21, b, num_kv, g, head_dim, block_size, max_blocks, num_slots=256
    )
    scale = head_dim**-0.5
    ref = ref_ops.paged_decode_attention_xla(
        jnp.asarray(q), jnp.asarray(k_cache), jnp.asarray(v_cache),
        jnp.asarray(bt), jnp.asarray(cl), block_size, scale,
    )
    got = ragged_decode_pallas(q, k_cache, v_cache, bt, cl, block_size,
                               scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ragged_decode_nonpow2_batch():
    """Non-power-of-two batch widths must not mis-pad the in-kernel
    query-block grid (the dense-schedule t_pad regression class)."""
    b, num_kv, g, head_dim, block_size, max_blocks = 11, 2, 2, 64, 16, 4
    q, k_cache, v_cache, bt, cl = make_paged_case(
        23, b, num_kv, g, head_dim, block_size, max_blocks, num_slots=1024
    )
    scale = head_dim**-0.5
    ref = ref_ops.paged_decode_attention_xla(
        jnp.asarray(q), jnp.asarray(k_cache), jnp.asarray(v_cache),
        jnp.asarray(bt), jnp.asarray(cl), block_size, scale,
    )
    got = ragged_decode_pallas(q, k_cache, v_cache, bt, cl, block_size,
                               scale)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
