"""Test client library (analog of the reference's tests/utils.py).

Reusable synchronous ``GrpcClient`` over the hand-written service stubs,
a ``wait_until`` poller, and random free-port allocation.
"""

from __future__ import annotations

import socket
import time
from typing import Callable, Optional

import grpc

from vllm_tgis_adapter_tpu.grpc.pb import generation_pb2 as pb2
from vllm_tgis_adapter_tpu.grpc.pb.rpc import GenerationServiceStub


def get_random_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def wait_until(
    pred: Callable[[], bool],
    timeout: float = 120.0,
    pause: float = 0.5,
) -> None:
    start = time.monotonic()
    exc = None
    while (time.monotonic() - start) < timeout:
        try:
            if pred():
                return
            exc = None
        except Exception as e:  # noqa: BLE001
            exc = e
        time.sleep(pause)
    raise TimeoutError(f"timed out waiting for {pred}: last error: {exc}")


class GrpcClient:
    """Synchronous client for the fmaas.GenerationService API."""

    def __init__(
        self,
        host: str,
        port: int,
        *,
        insecure: bool = True,
        ca_cert: Optional[bytes] = None,
        client_cert: Optional[bytes] = None,
        client_key: Optional[bytes] = None,
    ):
        target = f"{host}:{port}"
        if insecure:
            self.channel = grpc.insecure_channel(target)
        else:
            credentials = grpc.ssl_channel_credentials(
                root_certificates=ca_cert,
                private_key=client_key,
                certificate_chain=client_cert,
            )
            self.channel = grpc.secure_channel(target, credentials)
        self.stub = GenerationServiceStub(self.channel)

    def __enter__(self) -> "GrpcClient":
        return self

    def __exit__(self, *exc_info) -> None:  # noqa: ANN002
        self.channel.close()

    # ------------------------------------------------------------------ RPCs

    def make_request(
        self,
        text: str | list[str],
        model_id: str = "",
        *,
        adapter_id: Optional[str] = None,
        max_new_tokens: Optional[int] = None,
        sampling: bool = False,
        seed: Optional[int] = None,
        metadata: Optional[list[tuple[str, str]]] = None,
        params: Optional[pb2.Parameters] = None,
        timeout: float = 60,
    ):
        texts = [text] if isinstance(text, str) else text
        if params is None:
            params = pb2.Parameters(
                method=(
                    pb2.DecodingMethod.SAMPLE
                    if sampling
                    else pb2.DecodingMethod.GREEDY
                ),
                stopping=pb2.StoppingCriteria(
                    max_new_tokens=max_new_tokens or 10
                ),
            )
            if seed is not None:
                params.sampling.seed = seed
        request = pb2.BatchedGenerationRequest(
            model_id=model_id,
            requests=[pb2.GenerationRequest(text=t) for t in texts],
            params=params,
        )
        if adapter_id is not None:
            request.adapter_id = adapter_id
        response = self.stub.Generate(
            request, metadata=metadata or [], timeout=timeout
        )
        if isinstance(text, str):
            return response.responses[0]
        return list(response.responses)

    def make_request_stream(
        self,
        text: str,
        model_id: str = "",
        *,
        adapter_id: Optional[str] = None,
        max_new_tokens: Optional[int] = None,
        params: Optional[pb2.Parameters] = None,
        metadata: Optional[list[tuple[str, str]]] = None,
        timeout: float = 60,
    ):
        if params is None:
            params = pb2.Parameters(
                stopping=pb2.StoppingCriteria(max_new_tokens=max_new_tokens or 10)
            )
        request = pb2.SingleGenerationRequest(
            model_id=model_id,
            request=pb2.GenerationRequest(text=text),
            params=params,
        )
        if adapter_id is not None:
            request.adapter_id = adapter_id
        return list(
            self.stub.GenerateStream(
                request, metadata=metadata or [], timeout=timeout
            )
        )

    def make_request_tokenize(
        self,
        text: str | list[str],
        model_id: str = "",
        *,
        adapter_id: Optional[str] = None,
        return_tokens: bool = False,
        return_offsets: bool = False,
        truncate_input_tokens: int = 0,
        timeout: float = 60,
    ):
        texts = [text] if isinstance(text, str) else text
        request = pb2.BatchedTokenizeRequest(
            model_id=model_id,
            requests=[pb2.TokenizeRequest(text=t) for t in texts],
            return_tokens=return_tokens,
            return_offsets=return_offsets,
            truncate_input_tokens=truncate_input_tokens,
        )
        if adapter_id is not None:
            request.adapter_id = adapter_id
        response = self.stub.Tokenize(request, timeout=timeout)
        if isinstance(text, str):
            return response.responses[0]
        return list(response.responses)

    def model_info(self, model_id: str = "", timeout: float = 60):
        return self.stub.ModelInfo(
            pb2.ModelInfoRequest(model_id=model_id), timeout=timeout
        )

    def health_check(self, timeout: float = 5) -> bool:
        from vllm_tgis_adapter_tpu.grpc.health import HealthStub
        from vllm_tgis_adapter_tpu.grpc.pb.health_pb2 import (
            HealthCheckRequest,
            HealthCheckResponse,
        )

        response = HealthStub(self.channel).Check(
            HealthCheckRequest(service="fmaas.GenerationService"),
            timeout=timeout,
        )
        return response.status == HealthCheckResponse.SERVING
